"""MultiRaft: the batched host driver for G raft groups on one node
(the BASELINE.json north star's `MultiRaft<S: Storage>` alongside RawNode).

A TiKV-style multi-raft node is one peer of each of G groups.  The naive
driver calls `RawNode.tick()` G times per tick interval — an O(G) Python/
branching loop that dominates CPU at 100k groups even when nothing happens.
Here the per-group timer state {state, election_elapsed, heartbeat_elapsed,
randomized_timeout, promotable} lives in host numpy mirrors; each tick()
makes ONE device round-trip (upload mirrors → fused tick_kernel → download
counters + event masks) and then touches ONLY the groups whose masks fired
(want_campaign / want_heartbeat / election-timeout boundary) — the Zipf
sparsity BASELINE config #3 banks on.

Consistency contract: the mirrors are authoritative between host events; any
host interaction with a group (messages, proposals, Ready handling) is
bracketed by `_sync_to_node` / `_sync_from_node`, so the scalar RawNode sees
exactly the counters `Raft.tick()` would have produced (reference:
raft.rs:1024-1079 tick semantics, including the leader's election-timeout
boundary effects: check-quorum step and leader-transfer abort,
raft.rs:1056-1065).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config, HealthConfig
from ..eraftpb import Message, MessageType
from ..errors import RaftError
from ..raft import StateRole, new_message
from ..raw_node import RawNode
from ..storage import Storage
from . import kernels
from .health import HealthMonitor


class MultiRaft:
    """G RawNodes with device-batched tick timers."""

    _HEALTH_EVERY = 128  # ticks between automatic health-summary records

    def __init__(
        self,
        base_config: Config,
        storages: Sequence[Storage],
        group_seeds: Optional[Sequence[int]] = None,
        health: Optional[HealthConfig] = None,
    ):
        self.G = len(storages)
        self.nodes: List[RawNode] = []
        for g, store in enumerate(storages):
            cfg = Config(**{**base_config.__dict__})
            cfg.timeout_seed = (
                group_seeds[g] if group_seeds is not None else g
            )
            self.nodes.append(RawNode(cfg, store))
        self.election_tick = base_config.election_tick
        self.heartbeat_tick = base_config.heartbeat_tick
        # Shared observability plane: the per-group Config copies above all
        # carry the same Metrics reference, so every scalar node reports
        # into one registry; the driver adds its own multiraft_* series.
        self.metrics = base_config.metrics

        # Host-side mirrors [G] (authoritative between host events).
        self._state = np.array([n.raft.state for n in self.nodes], np.int32)
        self._ee = np.array(
            [n.raft.election_elapsed for n in self.nodes], np.int32
        )
        self._hb = np.array(
            [n.raft.heartbeat_elapsed for n in self.nodes], np.int32
        )
        self._rt = np.array(
            [n.raft.randomized_election_timeout for n in self.nodes], np.int32
        )
        self._promotable = np.array(
            [n.raft.promotable for n in self.nodes], bool
        )
        # Consensus-cursor mirrors feeding the health planes (authoritative
        # between host events like the timer mirrors above).
        self._leader = np.array(
            [n.raft.leader_id for n in self.nodes], np.int64
        )
        self._term = np.array([n.raft.term for n in self.nodes], np.int64)
        self._commit = np.array(
            [n.raft.raft_log.committed for n in self.nodes], np.int64
        )

        # Ready-scan short-circuit: groups that MIGHT have readiness.  A
        # RawNode only becomes ready through a host interaction (tick side
        # effects, step/propose/advance, or direct node() access), so every
        # such path marks its group here and ready_groups() probes only the
        # marked set — idle groups cost zero host work per tick.
        self._maybe_ready = set(range(self.G))

        # Fleet-health planes (numpy, this node's view of each group).
        # vote splits are not observable from one peer — that plane lives
        # on the device sim only (docs/OBSERVABILITY.md "Fleet health").
        # Deliberately int64: these are HOST accumulators outside the
        # GC007/GC008 int32 device-plane contract, so they never wrap and
        # need no drain cadence (docs/STATIC_ANALYSIS.md, GC008 table).
        self.health_config = health
        self.health_monitor: Optional[HealthMonitor] = None
        if health is not None:
            health.validate()
            self.health_monitor = HealthMonitor(
                metrics=base_config.metrics,
                recorder_size=health.recorder_size,
                snapshot_fn=self.explain,
            )
            self._h_leaderless = np.zeros(self.G, np.int64)
            self._h_since_commit = np.zeros(self.G, np.int64)
            self._h_term_bumps = np.zeros(self.G, np.int64)
            self._h_prev_commit = self._commit.copy()
            self._h_prev_term = self._term.copy()
            self._h_window_pos = 0
            self._h_ticks = 0
            # Time-to-reelect accounting (the host twin of the chaos
            # engine's device-side MTTR stats — chaos.update_chaos_stats):
            # an episode ends when a leaderless group regains a leader.
            self._h_reelections = 0
            self._h_healed_ticks = 0
            self._h_max_streak = 0
            self._h_leaderless_ticks_total = 0

        et, ht = self.election_tick, self.heartbeat_tick

        @jax.jit
        def _tick(state, ee, hb, rt, promotable):
            return kernels.tick_kernel(state, ee, hb, rt, promotable, et, ht)

        self._tick_fn = _tick

    # --- host<->mirror row sync ---

    def _sync_to_node(self, g: int) -> None:
        r = self.nodes[g].raft
        r.election_elapsed = int(self._ee[g])
        r.heartbeat_elapsed = int(self._hb[g])

    def _sync_from_node(self, g: int) -> None:
        r = self.nodes[g].raft
        self._state[g] = r.state
        self._ee[g] = r.election_elapsed
        self._hb[g] = r.heartbeat_elapsed
        self._rt[g] = r.randomized_election_timeout
        self._promotable[g] = r.promotable
        self._leader[g] = r.leader_id
        self._term[g] = r.term
        self._commit[g] = r.raft_log.committed

    # --- the batched tick (SURVEY.md §7 kernel k1 in production shape) ---

    def tick(self) -> np.ndarray:
        """Advance every group's logical clock by one tick with a single
        fused device kernel; dispatch tick side effects on the host only for
        fired groups.  Returns the boolean [G] mask of active groups."""
        m = self.metrics
        t0 = time.perf_counter() if m is not None else 0.0
        ee, hb, campaign, beat, checkq = self._tick_fn(
            jnp.asarray(self._state, dtype=jnp.int32),
            jnp.asarray(self._ee, dtype=jnp.int32),
            jnp.asarray(self._hb, dtype=jnp.int32),
            jnp.asarray(self._rt, dtype=jnp.int32),
            jnp.asarray(self._promotable, dtype=bool),
        )
        # np.array copies: jax array views are read-only.
        self._ee = np.array(ee)
        self._hb = np.array(hb)
        campaign = np.asarray(campaign)
        beat = np.asarray(beat)
        checkq = np.asarray(checkq)
        active = campaign | beat | checkq
        if m is not None:
            # The np conversions above block on the device, so t0..now spans
            # the full upload -> kernel -> download round trip.
            m.on_driver_tick(
                n_active=int(active.sum()),
                n_campaign=int(campaign.sum()),
                n_beat=int(beat.sum()),
                n_checkq=int(checkq.sum()),
                sync_seconds=time.perf_counter() - t0,
            )
        if not active.any():
            self._update_health()
            return active
        for g in np.nonzero(active)[0]:
            g = int(g)
            self._maybe_ready.add(g)
            node = self.nodes[g]
            r = node.raft
            self._sync_to_node(g)
            # Tick side effects drop only protocol-level step errors, like
            # Raft.tick's internal `let _ = self.step(...)` (reference:
            # raft.rs:1037-1047); real bugs (assertions etc.) propagate.
            if campaign[g]:
                # tick_election fired (reference: raft.rs:1037-1047).
                try:
                    r.step(new_message(0, MessageType.MsgHup, r.id))
                except RaftError:
                    pass
            if checkq[g]:
                # Leader election-timeout boundary (reference:
                # raft.rs:1056-1065): check-quorum + transfer abort.
                if r.check_quorum:
                    try:
                        r.step(new_message(0, MessageType.MsgCheckQuorum, r.id))
                    except RaftError:
                        pass
                if r.state == StateRole.Leader and r.lead_transferee is not None:
                    r.abort_leader_transfer()
            if beat[g] and r.state == StateRole.Leader:
                try:
                    r.step(new_message(0, MessageType.MsgBeat, r.id))
                except RaftError:
                    pass
            self._sync_from_node(g)
        self._update_health()
        return active

    # --- fleet health (this node's per-group view; numpy planes) ---

    def _update_health(self) -> None:
        """Per-tick vectorized health fold over the cursor mirrors (no
        Python per-group loop — this must stay O(G) numpy, not O(G)
        interpreter).  Units are driver TICKS (the sim planes count
        protocol rounds)."""
        hc = self.health_config
        if hc is None:
            return
        has_leader = self._leader != 0
        healed = has_leader & (self._h_leaderless > 0)
        self._h_reelections += int(healed.sum())
        self._h_healed_ticks += int(self._h_leaderless[healed].sum())
        self._h_leaderless = np.where(has_leader, 0, self._h_leaderless + 1)
        self._h_max_streak = max(
            self._h_max_streak, int(self._h_leaderless.max(initial=0))
        )
        self._h_leaderless_ticks_total += int((~has_leader).sum())
        advanced = self._commit > self._h_prev_commit
        self._h_since_commit = np.where(
            advanced, 0, self._h_since_commit + 1
        )
        np.copyto(self._h_prev_commit, self._commit)
        if self._h_window_pos == 0:
            self._h_term_bumps[:] = 0
        self._h_term_bumps += self._term - self._h_prev_term
        np.copyto(self._h_prev_term, self._term)
        self._h_window_pos = (self._h_window_pos + 1) % hc.window
        self._h_ticks += 1
        if (
            self.health_monitor is not None
            and self._h_ticks % self._HEALTH_EVERY == 0
        ):
            self.health_monitor.record(self._health_summary())

    def _health_summary(self) -> Dict[str, object]:
        """The same fixed-size summary shape ClusterSim.health() emits
        (vote-split facts excluded: not observable from one peer)."""
        hc = self.health_config
        assert hc is not None
        lag = self._h_since_commit
        leaderless = self._h_leaderless
        # HEALTH_COUNT_NAMES order (kernels.HS_* indices).
        counts = [
            int((leaderless > 0).sum()),
            int((leaderless >= hc.leaderless_stall_ticks).sum()),
            int((lag >= hc.commit_stall_ticks).sum()),
            int((self._h_term_bumps >= hc.churn_bumps).sum()),
        ]
        bounds = np.asarray(kernels.LAG_BUCKET_BOUNDS, np.int64)
        bucket = (lag[:, None] >= bounds[None, :]).sum(axis=1)
        hist = np.bincount(bucket, minlength=kernels.N_LAG_BUCKETS)
        score = np.maximum(lag, leaderless)
        k = min(hc.topk, self.G)
        order = np.argsort(-score, kind="stable")[:k]
        return HealthMonitor.summary_dict(counts, hist, order, score[order])

    def mttr(self) -> Dict[str, object]:
        """Time-to-reelect facts off the health planes, in driver TICKS
        (the host twin of the chaos engine's per-scenario MTTR report —
        docs/OBSERVABILITY.md "Chaos"): mean leaderless-episode length
        over episodes that ended with a leader regained, plus the worst
        streak and the cumulative leaderless (group, tick) count."""
        if self.health_config is None:
            raise RuntimeError(
                "health disabled; construct MultiRaft with "
                "health=HealthConfig(...)"
            )
        return {
            "mttr_ticks": (
                round(self._h_healed_ticks / self._h_reelections, 3)
                if self._h_reelections
                else None
            ),
            "reelections": self._h_reelections,
            "max_leaderless_streak": self._h_max_streak,
            "leaderless_group_ticks": self._h_leaderless_ticks_total,
        }

    def health(self) -> Dict[str, object]:
        """Current fleet-health summary (requires the health=HealthConfig
        constructor arg); also pushed to the flight recorder."""
        if self.health_config is None:
            raise RuntimeError(
                "health disabled; construct MultiRaft with "
                "health=HealthConfig(...)"
            )
        summary = self._health_summary()
        if self.health_monitor is not None:
            self.health_monitor.record(summary)
        return summary

    def explain(self, group_id: int) -> Dict[str, object]:
        """Post-mortem for one group: health-plane row + this peer's
        consensus cursors (worst-offender snapshots in the flight recorder
        come through here)."""
        r = self.nodes[group_id].raft
        out: Dict[str, object] = {
            "group": int(group_id),
            "term": int(r.term),
            "state": int(r.state),
            "leader_id": int(r.leader_id),
            "commit": int(r.raft_log.committed),
            "last_index": int(r.raft_log.last_index()),
        }
        if self.health_config is not None:
            out["health"] = {
                "leaderless_ticks": int(self._h_leaderless[group_id]),
                "ticks_since_commit": int(self._h_since_commit[group_id]),
                "term_bumps_in_window": int(self._h_term_bumps[group_id]),
            }
        return out

    # --- host-side per-group interactions (all bracketed by sync) ---

    def _host_op(self, g: int, fn: Callable[[RawNode], object]):
        self._sync_to_node(g)
        self._maybe_ready.add(g)
        try:
            return fn(self.nodes[g])
        finally:
            self._sync_from_node(g)

    def step(self, g: int, m: Message) -> None:
        self._host_op(g, lambda n: n.step(m))

    def step_batch(self, msgs: Iterable[Tuple[int, Message]]) -> None:
        """Deliver a batch of (group, message) pairs (the DCN inbox path,
        SURVEY.md §5.8b)."""
        by_group: Dict[int, List[Message]] = {}
        for g, m in msgs:
            by_group.setdefault(g, []).append(m)
        for g in sorted(by_group):
            self._sync_to_node(g)
            self._maybe_ready.add(g)
            for m in by_group[g]:
                # Inbox delivery ignores protocol step errors only (the DCN
                # receive path mirrors the harness pump's discipline).
                try:
                    self.nodes[g].step(m)
                except RaftError:
                    pass
            self._sync_from_node(g)

    def propose(self, g: int, context: bytes, data: bytes) -> None:
        self._host_op(g, lambda n: n.propose(context, data))

    def campaign(self, g: int) -> None:
        self._host_op(g, lambda n: n.campaign())

    def transfer_leader(self, g: int, transferee: int) -> None:
        """Begin transferring group `g`'s leadership to peer `transferee`
        (RawNode::transfer_leader — the autopilot's admin action on the
        host driver path; the batched sim's twin is
        sim.step(transfer_propose=))."""
        self._host_op(g, lambda n: n.transfer_leader(transferee))

    def transfer_pending(self) -> int:
        """Groups with a leader transfer in flight (this node leading with
        lead_transferee set); also published as the
        health_groups_transfer_pending gauge when metrics are enabled."""
        pending = sum(
            1 for n in self.nodes if n.raft.lead_transferee is not None
        )
        m = self.metrics
        if m is not None:
            m.health_transfer_pending.set(pending)
        return pending

    def autopilot_report(self) -> Dict[str, object]:
        """The driver-side autopilot surface: current transfer-pending
        count, the MTTR facts (when health is on), and the most recent
        autopilot flight-recorder entry from the attached monitor (the
        batched Autopilot records its run reports there)."""
        out: Dict[str, object] = {
            "transfer_pending": self.transfer_pending(),
        }
        if self.health_config is not None:
            out["mttr"] = self.mttr()
        if self.health_monitor is not None:
            for entry in reversed(self.health_monitor.summary_ring()):
                if "autopilot" in entry:
                    out["last_run"] = entry["autopilot"]
                    break
        return out

    def has_ready(self, g: int) -> bool:
        return self.nodes[g].has_ready()

    def ready_groups(self) -> List[int]:
        """Groups with pending readiness.

        Short-circuited by the `_maybe_ready` dirty set: only groups some
        host interaction touched since they last probed not-ready are
        scanned — the device fired-masks already tell the tick which groups
        those are, so a quiescent fleet costs ZERO per-group host work here
        instead of an O(G) has_ready() sweep.  The scanned/skipped split is
        recorded on the metrics plane (the skip ratio)."""
        dirty = self._maybe_ready
        out: List[int] = []
        still: set = set()
        for g in sorted(dirty):
            if self.nodes[g].has_ready():
                out.append(g)
                still.add(g)
        m = self.metrics
        if m is not None:
            m.on_ready_scan(scanned=len(dirty), skipped=self.G - len(dirty))
        self._maybe_ready = still
        return out

    def ready(self, g: int):
        return self._host_op(g, lambda n: n.ready())

    def advance(self, g: int, rd):
        return self._host_op(g, lambda n: n.advance(rd))

    def advance_apply(self, g: int) -> None:
        self._host_op(g, lambda n: n.advance_apply())

    def node(self, g: int) -> RawNode:
        # Handing out the RawNode lets the caller mutate it behind our
        # back, so conservatively mark the group for the next ready scan.
        self._maybe_ready.add(g)
        return self.nodes[g]

    # --- batched introspection (SURVEY.md §5.5 MultiRaftStatus) ---

    def status(self) -> Dict[str, object]:
        states = self._state
        commits = np.array(
            [n.raft.raft_log.committed for n in self.nodes], np.int64
        )
        terms = np.array([n.raft.term for n in self.nodes], np.int64)
        out: Dict[str, object] = {
            "n_groups": self.G,
            "n_leaders": int((states == StateRole.Leader).sum()),
            "n_candidates": int((states == StateRole.Candidate).sum()),
            "min_commit": int(commits.min()) if self.G else 0,
            "total_commit": int(commits.sum()),
            "max_term": int(terms.max()) if self.G else 0,
        }
        if self.metrics is not None:
            out["metrics"] = self.metrics_snapshot()
        if self.health_monitor is not None:
            # The forensics surface (ISSUE 15): incidents the attached
            # monitor has recorded — from a device black box
            # (ClusterSim's drain) or any other record_incident caller —
            # summarized as cumulative per-slot counts plus the most
            # recent incident, so an operator's status poll can never
            # miss a tripped invariant.
            incidents = self.health_monitor.incidents()
            counts: Dict[str, int] = {}
            for inc in incidents:
                slot = inc.get("slot", "unknown")
                counts[slot] = max(counts.get(slot, 0), inc.get("count", 0))
            out["forensics"] = {
                "incidents": len(incidents),
                "counts": counts,
                "last": incidents[-1] if incidents else None,
            }
        return out

    def metrics_snapshot(self) -> Dict[str, float]:
        """Flat {sample_name: value} view of the shared registry (empty when
        metrics are disabled); `self.metrics.registry.expose()` gives the
        Prometheus text form."""
        if self.metrics is None:
            return {}
        return self.metrics.registry.snapshot()
