"""MultiRaft: the batched host driver for G raft groups on one node
(the BASELINE.json north star's `MultiRaft<S: Storage>` alongside RawNode).

A TiKV-style multi-raft node is one peer of each of G groups.  The naive
driver calls `RawNode.tick()` G times per tick interval — an O(G) Python/
branching loop that dominates CPU at 100k groups even when nothing happens.
Here the per-group timer state {state, election_elapsed, heartbeat_elapsed,
randomized_timeout, promotable} is mirrored into device-resident [G] arrays
and one fused `tick_kernel` advances every group per tick; the host then
touches ONLY the groups whose masks fired (want_campaign / want_heartbeat /
election-timeout boundary) plus groups with inbound traffic — the Zipf
sparsity BASELINE config #3 banks on.

Consistency contract: the device owns the timers between host events; any
host interaction with a group (messages, proposals, Ready handling) is
bracketed by `_sync_to_node` / `_sync_from_node`, which gather/scatter that
group's row so the scalar RawNode sees exactly the counters `Raft.tick()`
would have produced (reference: raft.rs:1024-1079 tick semantics, including
the leader's election-timeout boundary effects: check-quorum step and
leader-transfer abort, raft.rs:1056-1065).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..eraftpb import Message, MessageType
from ..raft import StateRole, new_message
from ..raw_node import RawNode
from ..storage import Storage
from . import kernels


class MultiRaft:
    """G RawNodes with device-batched tick timers."""

    def __init__(
        self,
        base_config: Config,
        storages: Sequence[Storage],
        group_seeds: Optional[Sequence[int]] = None,
    ):
        self.G = len(storages)
        self.nodes: List[RawNode] = []
        for g, store in enumerate(storages):
            cfg = Config(**{**base_config.__dict__})
            cfg.timeout_seed = (
                group_seeds[g] if group_seeds is not None else g
            )
            self.nodes.append(RawNode(cfg, store))
        self.election_tick = base_config.election_tick
        self.heartbeat_tick = base_config.heartbeat_tick

        # Device mirrors [G].
        self._d = {
            "state": jnp.asarray(
                np.array([n.raft.state for n in self.nodes], np.int32)
            ),
            "ee": jnp.asarray(
                np.array([n.raft.election_elapsed for n in self.nodes], np.int32)
            ),
            "hb": jnp.asarray(
                np.array(
                    [n.raft.heartbeat_elapsed for n in self.nodes], np.int32
                )
            ),
            "rt": jnp.asarray(
                np.array(
                    [n.raft.randomized_election_timeout for n in self.nodes],
                    np.int32,
                )
            ),
            "promotable": jnp.asarray(
                np.array([n.raft.promotable for n in self.nodes], bool)
            ),
        }

        et, ht = self.election_tick, self.heartbeat_tick

        @jax.jit
        def _tick(d):
            ee, hb, campaign, beat, checkq = kernels.tick_kernel(
                d["state"], d["ee"], d["hb"], d["rt"], d["promotable"], et, ht
            )
            out = dict(d)
            out["ee"] = ee
            out["hb"] = hb
            return out, campaign, beat, checkq

        self._tick_fn = _tick

    # --- host<->device row sync ---

    def _sync_to_node(self, g: int, ee_row: int, hb_row: int) -> None:
        r = self.nodes[g].raft
        r.election_elapsed = int(ee_row)
        r.heartbeat_elapsed = int(hb_row)

    def _sync_from_nodes(self, groups: Iterable[int]) -> None:
        groups = list(groups)
        if not groups:
            return
        idx = jnp.asarray(np.asarray(groups, np.int32))
        vals = {
            "state": np.array(
                [self.nodes[g].raft.state for g in groups], np.int32
            ),
            "ee": np.array(
                [self.nodes[g].raft.election_elapsed for g in groups], np.int32
            ),
            "hb": np.array(
                [self.nodes[g].raft.heartbeat_elapsed for g in groups], np.int32
            ),
            "rt": np.array(
                [self.nodes[g].raft.randomized_election_timeout for g in groups],
                np.int32,
            ),
            "promotable": np.array(
                [self.nodes[g].raft.promotable for g in groups], bool
            ),
        }
        for k, v in vals.items():
            self._d[k] = self._d[k].at[idx].set(jnp.asarray(v))

    # --- the batched tick (SURVEY.md §7 kernel k1 in production shape) ---

    def tick(self) -> np.ndarray:
        """Advance every group's logical clock by one tick on device;
        dispatch tick side effects on the host only for fired groups.
        Returns the boolean [G] mask of groups with probable readiness."""
        self._d, campaign, beat, checkq = self._tick_fn(self._d)
        campaign = np.asarray(campaign)
        beat = np.asarray(beat)
        checkq = np.asarray(checkq)
        active = campaign | beat | checkq
        if not active.any():
            return active
        idx = np.nonzero(active)[0]
        ee = np.asarray(jnp.take(self._d["ee"], jnp.asarray(idx)))
        hb = np.asarray(jnp.take(self._d["hb"], jnp.asarray(idx)))
        touched = []
        for row, g in enumerate(idx):
            g = int(g)
            node = self.nodes[g]
            r = node.raft
            self._sync_to_node(g, ee[row], hb[row])
            if campaign[g]:
                # tick_election fired (reference: raft.rs:1037-1047).
                try:
                    r.step(new_message(0, MessageType.MsgHup, r.id))
                except Exception:
                    pass
            if checkq[g]:
                # Leader election-timeout boundary (reference:
                # raft.rs:1056-1065): check-quorum + transfer abort.
                if r.check_quorum:
                    try:
                        r.step(new_message(0, MessageType.MsgCheckQuorum, r.id))
                    except Exception:
                        pass
                if r.state == StateRole.Leader and r.lead_transferee is not None:
                    r.abort_leader_transfer()
            if beat[g] and r.state == StateRole.Leader:
                try:
                    r.step(new_message(0, MessageType.MsgBeat, r.id))
                except Exception:
                    pass
            touched.append(g)
        self._sync_from_nodes(touched)
        return active

    # --- host-side per-group interactions (all bracketed by sync) ---

    def _host_op(self, g: int, fn: Callable[[RawNode], object]):
        ee = int(self._d["ee"][g])
        hb = int(self._d["hb"][g])
        self._sync_to_node(g, ee, hb)
        try:
            return fn(self.nodes[g])
        finally:
            self._sync_from_nodes([g])

    def step(self, g: int, m: Message) -> None:
        self._host_op(g, lambda n: n.step(m))

    def step_batch(self, msgs: Iterable[Tuple[int, Message]]) -> None:
        """Deliver a batch of (group, message) pairs with ONE gather/scatter
        for all touched groups (the DCN inbox path, SURVEY.md §5.8b)."""
        by_group: Dict[int, List[Message]] = {}
        for g, m in msgs:
            by_group.setdefault(g, []).append(m)
        if not by_group:
            return
        groups = sorted(by_group)
        gidx = jnp.asarray(np.asarray(groups, np.int32))
        ee = np.asarray(jnp.take(self._d["ee"], gidx))
        hb = np.asarray(jnp.take(self._d["hb"], gidx))
        for row, g in enumerate(groups):
            self._sync_to_node(g, ee[row], hb[row])
            for m in by_group[g]:
                try:
                    self.nodes[g].step(m)
                except Exception:
                    pass
        self._sync_from_nodes(groups)

    def propose(self, g: int, context: bytes, data: bytes) -> None:
        self._host_op(g, lambda n: n.propose(context, data))

    def campaign(self, g: int) -> None:
        self._host_op(g, lambda n: n.campaign())

    def has_ready(self, g: int) -> bool:
        return self.nodes[g].has_ready()

    def ready_groups(self) -> List[int]:
        return [g for g, n in enumerate(self.nodes) if n.has_ready()]

    def ready(self, g: int):
        return self._host_op(g, lambda n: n.ready())

    def advance(self, g: int, rd):
        return self._host_op(g, lambda n: n.advance(rd))

    def advance_apply(self, g: int) -> None:
        self._host_op(g, lambda n: n.advance_apply())

    def node(self, g: int) -> RawNode:
        return self.nodes[g]

    # --- batched introspection (SURVEY.md §5.5 MultiRaftStatus) ---

    def status(self) -> Dict[str, int]:
        states = np.array([n.raft.state for n in self.nodes], np.int32)
        commits = np.array(
            [n.raft.raft_log.committed for n in self.nodes], np.int64
        )
        terms = np.array([n.raft.term for n in self.nodes], np.int64)
        return {
            "n_groups": self.G,
            "n_leaders": int((states == StateRole.Leader).sum()),
            "n_candidates": int((states == StateRole.Candidate).sum()),
            "min_commit": int(commits.min()) if self.G else 0,
            "total_commit": int(commits.sum()),
            "max_term": int(terms.max()) if self.G else 0,
        }
