"""ScalarCluster: the lockstep parity oracle for ClusterSim.

Runs G groups × P real scalar `Raft` instances through the harness Network's
persist-before-send pump, one protocol round at a time, with the same
(node, term)-keyed deterministic timeouts as the device sim.  A round is:
tick every peer (in peer order) → pump to quiescence → propose the round's
append workload at the acting leader → pump.

Commit-index parity between this and ClusterSim on identical crash/append
schedules is THE correctness claim of the batched backend (BASELINE.json's
"bit-identical commit indices").

The oracle family layers on top of ScalarCluster: HealthOracle folds the
numpy twin of the device health planes each round; ChaosOracle replays a
compiled fault schedule (chaos.HostSchedule) through it; TransferOracle
(ISSUE 12) drives the real RawNode::transfer_leader pump as a pre-tick
phase; ReadOracle (ISSUE 13) drives the real ReadOnlyOption::LeaseBased
and Safe read pumps on throwaway deep copies for per-round receipt
parity with sim.step(read_propose=); ReconfigOracle (ISSUE 10) walks a
compiled membership-churn schedule (reconfig.HostReconfigSchedule) —
proposing real conf entries, gating on the dual-majority commit, and
applying the Changer-computed config by scalar surgery — the exact twin
of reconfig.make_runner's scan.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..eraftpb import ConfState, Entry, Message, MessageType
from ..raft import StateRole
from ..raft_log import NO_LIMIT
from ..storage import MemStorage
from ..harness import Interface, Network


class ScalarCluster:
    def __init__(self, n_groups: int, n_peers: int, election_tick: int = 10,
                 heartbeat_tick: int = 1, voters=None, voters_outgoing=None,
                 learners=None, check_quorum: bool = False,
                 pre_vote: bool = False, metrics=None,
                 timeout_seed_base: int = 0):
        """`voters`/`voters_outgoing`/`learners` (peer-id lists) bootstrap
        every group in that (possibly joint) configuration; default: all
        peers voters.  `check_quorum`/`pre_vote` configure every Raft the
        reference way (raft.rs Config); since ISSUE 7 the device sim
        models both (SimConfig.check_quorum / pre_vote route rounds
        through the damped wave path), so damped parity schedules set the
        SAME flags on both sides (tests/test_damping_parity.py) while the
        undamped suites keep both False.  `metrics` (an optional
        raft_tpu.metrics.Metrics) is shared by every Raft in the cluster —
        the scalar side of the device counter-plane parity test.
        `timeout_seed_base` offsets every group's timeout_seed (group g
        draws from stream timeout_seed_base + g): the forensics one-group
        repro (raft_tpu/multiraft/forensics.py) replays GLOBAL group id g
        as a 1-group cluster on stream g, bit-identical to the fleet."""
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.networks: List[Network] = []
        for g in range(n_groups):
            config = Config(
                election_tick=election_tick,
                heartbeat_tick=heartbeat_tick,
                max_size_per_msg=NO_LIMIT,
                max_inflight_msgs=1 << 20,  # effectively unbounded window
                timeout_seed=timeout_seed_base + g,
                check_quorum=check_quorum,
                pre_vote=pre_vote,
                metrics=metrics,
            )
            if voters is None:
                peers: List[Optional[Interface]] = [None] * n_peers
                self.networks.append(Network.new_with_config(peers, config))
            else:
                from ..raft import Raft

                ifaces = []
                for id in range(1, n_peers + 1):
                    cs = ConfState(
                        voters=list(voters),
                        voters_outgoing=list(voters_outgoing or []),
                        learners=list(learners or []),
                    )
                    store = MemStorage.new_with_conf_state(cs)
                    cfg = Config(**{**config.__dict__, "id": id})
                    ifaces.append(Interface(Raft(cfg, store)))
                self.networks.append(
                    Network.new_with_config(ifaces, config)
                )

    def _apply_crash_mask(
        self,
        net: Network,
        crashed_row: Sequence[bool],
        link_row: Optional[np.ndarray] = None,
    ) -> None:
        """Install the round's faults as per-edge drops: whole-peer crashes
        (isolation) plus, when a `link_row[P, P]` reachability matrix is
        given, a 1.0 drop on every down DIRECTED link — the scalar half of
        the chaos engine's link plane (sim.step's `link=`)."""
        net.recover()
        for p, c in enumerate(crashed_row):
            if c:
                net.isolate(p + 1)
        if link_row is not None:
            for a in range(self.n_peers):
                for b in range(self.n_peers):
                    if a != b and not link_row[a, b]:
                        net.drop(a + 1, b + 1, 1.0)

    def round(self, crashed: Optional[np.ndarray] = None,
              append_n: Optional[np.ndarray] = None,
              link: Optional[np.ndarray] = None,
              conf_propose: Optional[np.ndarray] = None,
              kick: Optional[np.ndarray] = None):
        """One lockstep protocol round across all groups.

        crashed:  bool[G, P] whole-peer isolation for the round.
        append_n: int[G] workload proposed at each group's acting leader.
        link:     optional bool[P, P, G] directed reachability (peer-major
                  src/dst axes, like the device plane); a down link drops
                  every message on that edge for the whole round.
        conf_propose: optional bool[G] — groups whose pending conf-change
                  op proposes its entry this round (the scalar twin of
                  sim.step's reconfig_propose): ONE extra entry joins the
                  group's propose batch, appended LAST.  Returns a list of
                  per-group (owner, index, term) records — the acting
                  leader's id, the conf entry's log index, and the
                  leader's term at propose time, or (0, 0, 0) where no
                  alive leader acted — mirroring sim.ReconfigProposal
                  bit-for-bit.  Returns None when conf_propose is None.
        kick:     optional bool[G, P] — the autopilot campaign kick (the
                  scalar twin of sim.step's campaign_kick): a MsgHup
                  stepped at the peer right after its tick, i.e. the
                  RawNode::campaign admin call.  A kick lands only when
                  the peer's own election timer did NOT fire this tick
                  (the device ORs the two into one campaign), and MsgHup
                  itself enforces the leader/promotable gates (hup()).
        """
        if crashed is None:
            crashed = np.zeros((self.n_groups, self.n_peers), dtype=bool)
        if append_n is None:
            append_n = np.zeros((self.n_groups,), dtype=np.int64)
        props = (
            None
            if conf_propose is None
            else [(0, 0, 0)] * self.n_groups
        )
        for g, net in enumerate(self.networks):
            self._apply_crash_mask(
                net, crashed[g], None if link is None else link[:, :, g]
            )
            # Tick every peer in peer order, collecting outbound messages
            # with the pump's persist-before-send discipline.
            initial: List[Message] = []
            for p in range(1, self.n_peers + 1):
                peer = net.peers[p]
                fired = (
                    peer.raft.state != StateRole.Leader
                    and peer.raft.promotable
                    and peer.raft.election_elapsed + 1
                    >= peer.raft.randomized_election_timeout
                )
                peer.raft.tick()
                if kick is not None and bool(kick[g][p - 1]) and not fired:
                    peer.raft.step(
                        Message(msg_type=MessageType.MsgHup, from_=p, to=p)
                    )
                peer.persist()
                initial.extend(net.filter(peer.read_messages()))
            net.send(initial)
            # Propose the append workload at the acting leader (the alive
            # leader with the highest term).
            n = int(append_n[g])
            extra = conf_propose is not None and bool(conf_propose[g])
            total = n + (1 if extra else 0)
            if total > 0:
                lead = self.acting_leader(g, crashed[g])
                if lead is not None:
                    if extra:
                        # The conf entry's landing spot, captured BEFORE
                        # the propose pump (the leader appends the batch
                        # first thing; later traffic in the pump can
                        # depose it but never unappend) — matches the
                        # device extra's workload-stage snapshot.
                        r = net.peers[lead].raft
                        props[g] = (
                            lead,
                            r.raft_log.last_index() + total,
                            r.term,
                        )
                    ents = [Entry(data=b"x") for _ in range(total)]
                    net.send([
                        Message(
                            msg_type=MessageType.MsgPropose,
                            from_=lead,
                            to=lead,
                            entries=ents,
                        )
                    ])
        return props

    def acting_leader(self, g: int, crashed_row: Sequence[bool]) -> Optional[int]:
        best = None
        best_term = -1
        for p in range(1, self.n_peers + 1):
            if crashed_row[p - 1]:
                continue
            r = self.networks[g].peers[p].raft
            if r.state == StateRole.Leader and r.term > best_term:
                best, best_term = p, r.term
        return best

    # --- state extraction for parity comparison ---

    def snapshot(self) -> dict:
        G, P = self.n_groups, self.n_peers
        out = {
            k: np.zeros((G, P), dtype=np.int64)
            for k in ("term", "state", "commit", "last_index", "last_term")
        }
        for g in range(G):
            for p in range(P):
                r = self.networks[g].peers[p + 1].raft
                out["term"][g, p] = r.term
                out["state"][g, p] = r.state
                out["commit"][g, p] = r.raft_log.committed
                out["last_index"][g, p] = r.raft_log.last_index()
                out["last_term"][g, p] = r.raft_log.last_term()
        return out


def host_pack_bits_g(plane: np.ndarray) -> np.ndarray:
    """Numpy twin of kernels.pack_bits_g: pack a bool plane 32:1 along its
    LAST (group) axis into uint32 words (word w's bit j = group 32*w + j,
    zero-padded past G).  The GC010 oracle for the recent_active
    scan-carry packing — tests/test_multiraft_kernels.py asserts bit-exact
    equality with the device kernel at awkward widths."""
    plane = np.asarray(plane, dtype=bool)
    g = plane.shape[-1]
    n_words = (g + 31) // 32
    pad = n_words * 32 - g
    bits = plane.astype(np.uint32)
    if pad:
        bits = np.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    bits = bits.reshape(plane.shape[:-1] + (n_words, 32))
    lanes = np.arange(32, dtype=np.uint32)
    return (bits << lanes).sum(axis=-1).astype(np.uint32)


def host_unpack_bits_g(words: np.ndarray, g: int) -> np.ndarray:
    """Numpy twin of kernels.unpack_bits_g (inverse of host_pack_bits_g)."""
    words = np.asarray(words, dtype=np.uint32)
    lanes = np.arange(32, dtype=np.uint32)
    bits = (words[..., :, None] >> lanes) & np.uint32(1)
    flat = bits.reshape(words.shape[:-1] + (words.shape[-1] * 32,))
    return flat[..., :g] != 0


# Throwaway-clone Inflights window (slots).  Real harness clusters run
# max_inflight_msgs = 1 << 20 ("effectively unbounded"); clones carry a
# rebased ring of this size instead so a clone costs microseconds, not
# a 1M-slot buffer alloc per progress — see _seed_clone_memo.
_TWIN_CAP = 1 << 14


def _seed_clone_memo(net, memo: dict) -> dict:
    """Seed a deepcopy memo for one group's Network so the copy is exact
    AND cheap: per-store RLocks (unpicklable — a naive deepcopy raises)
    are re-seeded fresh, a shared metrics registry is dropped so the
    clone's pumps can never double-count the live cluster's events, and
    each Inflights ring — its buffer a flat int list preallocated to
    max_inflight_msgs (1 << 20 in the harness config), ~10M interned
    ints per network — is seeded with a rebased twin carrying only the
    LIVE window [start, start+count): slots outside it are never read
    before being overwritten (inflights.py's ring discipline), so the
    twin is observationally exact while skipping the full-buffer copies
    that made naive clones cost seconds each."""
    import threading

    for iface in net.peers.values():
        r = iface.raft
        if r is None:
            continue
        store = getattr(r.raft_log, "store", None)
        lock = getattr(store, "_lock", None)
        if lock is not None:
            memo[id(lock)] = threading.RLock()
        if r.metrics is not None:
            memo[id(r.metrics)] = None
        for _, pr in r.prs.iter():
            ins = pr.ins
            # Rebase the twin to start=0 on a small ring: only the live
            # window is observable (slots outside [start, start+count)
            # are never read before being overwritten), and the ONLY cap
            # dependence is full() at count == cap — unreachable below
            # _TWIN_CAP for any harness schedule (≤ a few hundred
            # in-flight appends even across a 110-round fuzz run with a
            # crashed follower).  A genuine backlog falls back to the
            # real window so full()-parity can never silently change.
            tcap = min(ins.cap, _TWIN_CAP)
            if ins.count > tcap // 4:
                tcap = ins.cap
            twin = type(ins)(tcap)
            twin.count = ins.count
            for i in range(ins.count):
                twin.buffer[i] = ins.buffer[(ins.start + i) % ins.cap]
            memo[id(ins)] = twin
    return memo


def clone_cluster(obj):
    """Memo-seeded deepcopy of a ScalarCluster — or of any oracle
    holding one as `.cluster` — in milliseconds where a naive deepcopy
    costs ~16s per clone (ROADMAP's standing tier-1 constraint) or
    aborts outright on the stores' RLocks.  The parity suites use this
    to settle ONE master cluster per configuration module-scoped and
    hand every test its own throwaway copy instead of re-running the
    settle; ReadOracle's per-probe `_clone_group` is the single-network
    special case of the same memo seeding."""
    import copy

    cluster = getattr(obj, "cluster", obj)
    memo: dict = {}
    for net in cluster.networks:
        _seed_clone_memo(net, memo)
    return copy.deepcopy(obj, memo)


class HealthOracle:
    """Scalar-side oracle for the device health planes (sim.HealthState).

    Maintains the same four per-group int32 planes — leaderless_ticks,
    ticks_since_commit, term_bumps_in_window, vote_splits (row order
    kernels.HP_*) — from OBSERVABLE scalar-cluster state, with the
    bit-identical fold rules of kernels.update_health:

      * has_leader:      some alive peer ends the round as Leader;
      * commit_advanced: the group's max commit index grew this round;
      * term_bump:       growth of the group's max term this round;
      * campaigned:      some peer's election timer fires this round —
                         computed BEFORE the round from the same facts as
                         kernels.tick_kernel (not-leader & promotable &
                         election_elapsed + 1 >= randomized timeout,
                         reference: raft.rs:1037-1047);
      * won:             some peer became leader during the round (Leader
                         at round end with a new term or a non-Leader
                         pre-round role — become_leader is the only path);
      * vote_split:      campaigned and nobody won.

    tests/test_health_parity.py asserts exact per-round equality of these
    planes against ClusterSim's device-maintained HealthState.

    This class is the resolved GC010 oracle symbol for the health kernels
    (tools/graftcheck/parity_obligations.json: zero_health/update_health
    -> simref.HealthOracle); renaming it or its `round` entry point is an
    obligation change and must go through `make obligations`.
    """

    def __init__(self, cluster: ScalarCluster, window: int = 32):
        self.cluster = cluster
        G = cluster.n_groups
        self.planes = np.zeros((4, G), dtype=np.int32)
        self.window = window
        self.window_pos = 0

    def _capture(self):
        G, P = self.cluster.n_groups, self.cluster.n_peers
        from ..raft import StateRole

        state = np.zeros((G, P), dtype=np.int64)
        term = np.zeros((G, P), dtype=np.int64)
        commit = np.zeros((G, P), dtype=np.int64)
        for g in range(G):
            for p in range(P):
                r = self.cluster.networks[g].peers[p + 1].raft
                state[g, p] = int(r.state)
                term[g, p] = r.term
                commit[g, p] = r.raft_log.committed
        return state, term, commit, int(StateRole.Leader)

    def _pre_round(self, crashed, link) -> None:
        """Hook between the pre-round capture and the want_campaign read:
        the TransferOracle's pre-tick transfer pump runs here (the device
        twin, sim._transfer_phase, runs before the round's ticks, so the
        tick-time campaign facts must be read AFTER it).  No-op here."""

    def round(self, crashed=None, append_n=None, link=None,
              conf_propose=None, kick=None):
        """Drive one cluster round and fold its health facts into the
        planes (the scalar twin of sim.step's health extra).  `link` is
        the optional bool[P, P, G] chaos reachability plane,
        `conf_propose` the optional bool[G] conf-entry propose mask, and
        `kick` the optional bool[G, P] campaign-kick mask, all passed
        through to ScalarCluster.round; returns its proposal records
        (None unless conf_propose is given).  A kicked campaign joins the
        `campaigned` health fact exactly like the device fold (the kick
        IS a campaign() call)."""
        G, P = self.cluster.n_groups, self.cluster.n_peers
        if crashed is None:
            crashed = np.zeros((G, P), dtype=bool)
        pre_state, pre_term, pre_commit, leader_code = self._capture()
        self._pre_round(crashed, link)
        want_campaign = np.zeros((G, P), dtype=bool)
        for g in range(G):
            for p in range(P):
                r = self.cluster.networks[g].peers[p + 1].raft
                want_campaign[g, p] = (
                    int(r.state) != leader_code
                    and r.promotable
                    and (
                        r.election_elapsed + 1
                        >= r.randomized_election_timeout
                        or (kick is not None and bool(kick[g][p]))
                    )
                )

        props = self.cluster.round(
            crashed, append_n, link, conf_propose, kick=kick
        )

        post_state, post_term, post_commit, _ = self._capture()
        alive = ~np.asarray(crashed, dtype=bool)
        has_leader = np.any((post_state == leader_code) & alive, axis=1)
        commit_adv = post_commit.max(axis=1) > pre_commit.max(axis=1)
        term_bump = (post_term.max(axis=1) - pre_term.max(axis=1)).astype(
            np.int32
        )
        won = np.any(
            (post_state == leader_code)
            & ((pre_state != leader_code) | (post_term > pre_term)),
            axis=1,
        )
        campaigned = np.any(want_campaign, axis=1)

        leaderless, since, bumps, splits = self.planes
        leaderless = np.where(has_leader, 0, leaderless + 1)
        since = np.where(commit_adv, 0, since + 1)
        if self.window_pos == 0:
            bumps = np.zeros_like(bumps)
        bumps = bumps + term_bump
        splits = splits + (campaigned & ~won).astype(np.int32)
        self.planes = np.stack([leaderless, since, bumps, splits]).astype(
            np.int32
        )
        self.window_pos = (self.window_pos + 1) % self.window
        return props


class ChaosOracle(HealthOracle):
    """Scalar-side oracle for chaos (link-fault) schedules.

    Replays a compiled fault schedule (chaos.HostSchedule — the numpy twin
    of the device schedule arrays, including the bit-identical per-round
    loss draws) through real Raft state machines: each round installs the
    round's effective link matrix as per-edge 1.0 drops on the harness
    Network, runs the standard lockstep round, and folds the same health
    facts as HealthOracle.  tests/test_chaos_parity.py asserts exact
    per-round equality of every peer's state AND the health planes against
    ClusterSim stepping the identical schedule through the link-gated
    device path (sim.step's `link=`).

    This class is the resolved GC010 oracle symbol for the chaos kernels
    (tools/graftcheck/parity_obligations.json: link_loss_draw /
    check_safety -> simref.ChaosOracle); renaming it or its entry points
    is an obligation change and must go through `make obligations`.
    """

    def __init__(self, cluster: ScalarCluster, schedule=None, window: int = 32):
        super().__init__(cluster, window=window)
        self.schedule = schedule
        self.round_idx = 0

    def scheduled_round(self) -> None:
        """Advance one round of the attached chaos.HostSchedule."""
        if self.schedule is None:
            raise RuntimeError("no schedule attached; pass schedule= or "
                               "call round(link=...) directly")
        link, crashed, append = self.schedule.masks(self.round_idx)
        self.round_idx += 1
        # Schedule planes are peer-major [P, G]; the scalar round wants
        # [G, P] crash rows.
        self.round(crashed=crashed.T, append_n=append, link=link)


class TransferOracle(HealthOracle):
    """Scalar-side oracle for the batched leader-transfer protocol
    (ISSUE 12): drives the REAL RawNode::transfer_leader machinery —
    handle_transfer_leader's validation/abort rules, the catch-up append,
    MsgTimeoutNow, hup(true)'s CAMPAIGN_TRANSFER forced election, the
    ProposalDropped gate, and the tick-time election-timeout abort —
    through the harness pump, one drain-cadence round at a time, exactly
    as sim._transfer_phase models it:

      * a round's `transfer_propose[g]` (1-based target, 0 = none) steps
        MsgTransferLeader at the group's acting leader BEFORE the ticks
        and pumps it to quiescence — a reachable transfer completes
        within the round (catch-up, TimeoutNow, forced election, noop
        commit), an unreachable one leaves lead_transferee pending;
      * a PENDING transfer is nudged each round with an empty catch-up
        append (`_maybe_send_append(allow_empty=True)` — the effect the
        heartbeat-response chain has in the full-message system), whose
        ack re-triggers the TimeoutNow check;
      * `kick[g][p]` steps MsgHup at tick time (the RawNode::campaign
        admin call — the autopilot's re-election kick).

    tests/test_transfer_batched.py asserts exact per-round equality of
    every peer's state AND the health planes against ClusterSim stepping
    identical schedules through the transfer-enabled device paths
    (plain, linked, and damped).

    This class is the resolved GC010 oracle symbol for the transfer
    kernels (tools/graftcheck/parity_obligations.json: apply_transfer ->
    simref.TransferOracle); renaming it or its entry points is an
    obligation change and must go through `make obligations`.
    """

    def __init__(self, cluster: ScalarCluster, window: int = 32):
        super().__init__(cluster, window=window)
        self._transfer_propose = None

    def round(self, crashed=None, append_n=None, link=None,
              conf_propose=None, kick=None, transfer_propose=None):
        """One round with optional transfer commands: the pre-tick pump
        runs in the `_pre_round` hook (after the health capture, before
        the want_campaign read — where the device phase sits)."""
        self._transfer_propose = transfer_propose
        return super().round(
            crashed, append_n, link, conf_propose, kick=kick
        )

    def pending(self) -> np.ndarray:
        """int64[G, P] lead_transferee per peer (0 = none) — the scalar
        twin of SimState.transferee for parity comparison."""
        G, P = self.cluster.n_groups, self.cluster.n_peers
        out = np.zeros((G, P), dtype=np.int64)
        for g in range(G):
            for p in range(P):
                r = self.cluster.networks[g].peers[p + 1].raft
                out[g, p] = r.lead_transferee or 0
        return out

    def _pre_round(self, crashed, link) -> None:
        tp = self._transfer_propose
        self._transfer_propose = None
        cl = self.cluster
        for g, net in enumerate(cl.networks):
            # The round's faults gate the pump (the parent round
            # re-installs the same masks afterwards — idempotent).
            cl._apply_crash_mask(
                net, crashed[g], None if link is None else link[:, :, g]
            )
            lead = cl.acting_leader(g, crashed[g])
            if lead is None:
                continue
            r = net.peers[lead].raft
            want = 0 if tp is None else int(tp[g])
            if want and want != (r.lead_transferee or 0):
                # The admin command reaches the leader out-of-band (the
                # autopilot talks to it directly), so it is stepped, not
                # routed through the faulted network.  The drain-cadence
                # pump probes unconditionally (the device phase has no
                # pause state), so a paused probe is resumed first.
                pr = r.prs.get_mut(want)
                if pr is not None:
                    pr.paused = False
                r.step(
                    Message(
                        msg_type=MessageType.MsgTransferLeader,
                        from_=want,
                        to=lead,
                    )
                )
            elif r.lead_transferee is not None:
                pr = r.prs.get_mut(r.lead_transferee)
                if pr is not None:
                    pr.paused = False
                    r._maybe_send_append(
                        r.lead_transferee, pr, allow_empty=True
                    )
            else:
                continue
            net.peers[lead].persist()
            net.send(net.filter(net.peers[lead].read_messages()))


class ReadOracle(TransferOracle):
    """Scalar-side oracle for the batched client-read path (ISSUE 13):
    drives the REAL scalar read pumps — `ReadOnlyOption::LeaseBased` for
    lease serves and `Safe` for the ReadIndex fallback arm — with exact
    per-round read-response parity (index, serve round, and the
    degraded-to-ReadIndex decision) against `sim.step(read_propose=)`.

    The scalar Safe probe PERTURBS its cluster (the ctx heartbeat
    broadcast resets timers, teaches commits, and under damping its
    low-term nudge deposes stale leaders), while the device read phase is
    a pure probe on the round-entry state; per-round receipt parity
    therefore runs each probe on a THROWAWAY `copy.deepcopy` of the
    group's Network — the pump's perturbation is confined to the copy and
    the lockstep state parity composes unchanged.  The lease DECISION
    itself comes from `lease_gate`, the host twin of the hardened
    `kernels.lease_read` gate (check-quorum leader naming itself, inside
    the lease window, committed in its own term, no pending transfer, and
    lease reads enabled): when it passes the oracle drives the LeaseBased
    pump, when a READ_LEASE request finds it failed the oracle marks the
    read DEGRADED and drives the Safe pump — including the
    transfer-pending rejection, where raft-rs itself would serve (a real
    LeaseBased soundness gap: MsgTimeoutNow's forced election bypasses
    leases) and the hardened gate degrades instead.

    Subclasses TransferOracle so transfer schedules compose: probes run
    BEFORE the pre-tick transfer pump, exactly where the device's read
    phase sits.

    This class is the resolved GC010 oracle symbol for the lease-read
    kernels (tools/graftcheck/parity_obligations.json: lease_read /
    check_safety's linearizability slots -> simref.ReadOracle); renaming
    it or its entry points is an obligation change and must go through
    `make obligations`.
    """

    # sim.READ_* twins (workload schedules carry these codes).
    READ_NONE = 0
    READ_SAFE = 1
    READ_LEASE = 2

    def __init__(self, cluster: ScalarCluster, election_tick: int = 10,
                 lease_read: bool = False, window: int = 32):
        super().__init__(cluster, window=window)
        self.election_tick = election_tick
        self.lease_read = lease_read
        self.last_receipts: Optional[list] = None
        self._probe_seq = 0

    def lease_gate(self, g: int, crashed_row) -> tuple:
        """(acting_leader_id or None, gate bool): the host twin of
        kernels.lease_read's holder gate evaluated at the group's acting
        leader, from OBSERVABLE scalar state."""
        cl = self.cluster
        lead = cl.acting_leader(g, crashed_row)
        if lead is None:
            return None, False
        r = cl.networks[g].peers[lead].raft
        # Quorum-active-NOW: the non-clearing read of the same flags the
        # check-quorum boundary read-and-clears (the device gate's
        # check_quorum_active over the CURRENT recent_active row — see
        # kernels.lease_read for why boundary-only is unsound).
        active = {id for id, pr in r.prs.iter() if pr.recent_active}
        active.add(r.id)
        ok = (
            self.lease_read
            and r.check_quorum
            and r.state == StateRole.Leader
            and r.leader_id == r.id
            and r.election_elapsed < self.election_tick
            and not r.lead_transferee
            and r.commit_to_current_term()
            and r.prs.has_quorum(active)
        )
        return lead, ok

    def _clone_group(self, g: int):
        """deepcopy one group's Network for a throwaway probe: per-store
        RLocks (unpicklable) are re-seeded fresh via the deepcopy memo,
        and a shared metrics registry is dropped from the copy so the
        probe's pump can never double-count the live cluster's events."""
        import copy

        net = self.cluster.networks[g]
        memo: dict = {}
        _seed_clone_memo(net, memo)
        return copy.deepcopy(net, memo)

    def read_probe(self, g: int, crashed_row, link_col, mode: int) -> tuple:
        """One group's read receipt for this round: (index, lease,
        degraded) — the scalar twin of sim.ReadReceipt's per-group lanes.
        Runs the real pump on a deep copy (see class docstring)."""
        if mode == self.READ_NONE:
            return -1, False, False
        lead, gate = self.lease_gate(g, crashed_row)
        lease = mode == self.READ_LEASE and gate
        degraded = mode == self.READ_LEASE and not lease
        if lead is None:
            return -1, False, degraded
        from ..read_only_option import ReadOnlyOption

        net = self._clone_group(g)
        self.cluster._apply_crash_mask(net, crashed_row, link_col)
        iface = net.peers[lead]
        iface.raft.read_only.option = (
            ReadOnlyOption.LeaseBased if lease else ReadOnlyOption.Safe
        )
        self._probe_seq += 1
        ctx = b"read-%d" % self._probe_seq
        before = len(iface.raft.read_states)
        net.send([
            Message(
                msg_type=MessageType.MsgReadIndex,
                from_=lead,
                to=lead,
                entries=[Entry(data=ctx)],
            )
        ])
        rs = iface.raft.read_states
        if len(rs) > before and bytes(rs[-1].request_ctx) == ctx:
            return rs[-1].index, lease, degraded
        return -1, lease, degraded

    def round(self, crashed=None, append_n=None, link=None,
              conf_propose=None, kick=None, transfer_propose=None,
              read_propose=None):
        """One lockstep round with optional per-group read commands
        (`read_propose[g]` in READ_* codes).  Probes run FIRST — on the
        round-entry state, before the transfer pump and the ticks, where
        the device read phase sits — and land in `self.last_receipts` as
        [(index, lease, degraded)] per group (None when read_propose is
        None)."""
        G, P = self.cluster.n_groups, self.cluster.n_peers
        if crashed is None:
            crashed = np.zeros((G, P), dtype=bool)
        if read_propose is None:
            self.last_receipts = None
        else:
            self.last_receipts = [
                self.read_probe(
                    g,
                    crashed[g],
                    None if link is None else link[:, :, g],
                    int(read_propose[g]),
                )
                for g in range(G)
            ]
        return super().round(
            crashed, append_n, link, conf_propose, kick=kick,
            transfer_propose=transfer_propose,
        )


class ReconfigOracle(HealthOracle):
    """Scalar-side oracle for compiled membership-churn schedules.

    Replays a compiled reconfig schedule (reconfig.HostReconfigSchedule —
    the numpy/python twin of the device schedule arrays, derived from the
    SAME Changer-validated chain walk), optionally composed with a chaos
    schedule (chaos.HostSchedule), through real Raft state machines:
    each round runs the standard lockstep round with the round's faults
    and the pending op's conf-entry propose (ScalarCluster.round's
    conf_propose), applies the IDENTICAL propose/gate/retry rules the
    device runner folds into its scan (reconfig.make_runner), and — when
    a group's gate fires — performs the scalar surgery mirror of
    kernels.apply_confchange on every peer of the group at once:
    tracker.apply_conf with the Changer-computed configuration + map
    delta (fresh rows get the added-node recent_active grace and the
    device model's paused-probe discipline), promotable refresh,
    leader-step-down for peers leaving the config (raw role/leader_id
    surgery — no become_follower timer side effects, matching the
    kernel), and the quorum-shrink commit pickup via Raft.maybe_commit
    (no broadcast — the round's ordinary traffic propagates it).

    tests/test_reconfig_parity.py asserts exact per-round equality of
    every peer's state AND the health planes against the device runner
    stepping the identical schedule.

    This class is the resolved GC010 oracle symbol for the reconfig
    kernels (tools/graftcheck/parity_obligations.json: apply_confchange /
    check_safety -> simref.ReconfigOracle); renaming it or its entry
    points is an obligation change and must go through
    `make obligations`.
    """

    def __init__(self, cluster: ScalarCluster, schedule,
                 chaos_schedule=None, window: int = 32):
        super().__init__(cluster, window=window)
        self.schedule = schedule
        self.chaos = chaos_schedule
        if chaos_schedule is not None:
            if chaos_schedule.n_rounds != schedule.n_rounds:
                raise ValueError(
                    "chaos and reconfig schedules disagree on rounds"
                )
            if chaos_schedule.n_peers != schedule.n_peers:
                raise ValueError(
                    "chaos and reconfig schedules disagree on peers"
                )
        G = cluster.n_groups
        self.round_idx = 0
        self.stage = np.zeros(G, dtype=np.int64)
        self.op_ptr = np.zeros(G, dtype=np.int64)
        self.prop_owner = np.zeros(G, dtype=np.int64)
        self.prop_index = np.zeros(G, dtype=np.int64)
        self.prop_term = np.zeros(G, dtype=np.int64)

    @staticmethod
    def _regime_start(raft) -> int:
        """First index of the leader's current-term regime in its own log
        (the device's term_start_index): a leader's log tail is its
        regime, so walk back while the term matches."""
        idx = raft.raft_log.last_index()
        if raft.raft_log.term_or(idx) != raft.term:
            return idx + 1  # defensive: no regime entries yet
        while idx > 1 and raft.raft_log.term_or(idx - 1) == raft.term:
            idx -= 1
        return idx

    def _apply_surgery(self, g: int, slot) -> None:
        """The scalar mirror of kernels.apply_confchange for ONE group:
        identical mask swap, tracker-row delta, step-down, and commit
        pickup on every peer simultaneously."""
        from ..confchange.changer import MapChangeType
        from ..tracker import Configuration

        net = self.cluster.networks[g]
        for p in range(1, self.cluster.n_peers + 1):
            r = net.peers[p].raft
            conf = Configuration(
                voters=slot.voters_inc, learners=slot.learners
            )
            conf.voters.outgoing.voters.update(slot.voters_out)
            conf.learners_next = set(slot.learners_next)
            changes = [
                (i, MapChangeType(ct)) for i, ct in slot.changes
            ]
            # Fresh rows start at the reference's next_idx; for an acting
            # leader the device probe model derives the first-probe prev
            # from its term-start cursor (sim.py's never-acked rule), so
            # the leader's fresh rows get next = its regime start.
            if r.state == StateRole.Leader:
                next_idx = self._regime_start(r)
            else:
                next_idx = r.raft_log.last_index() + 1
            r.prs.apply_conf(conf, changes, next_idx)
            for i, ct in changes:
                if ct == MapChangeType.Add:
                    # apply_conf granted recent_active (the added-node
                    # grace); the device additionally models the fresh
                    # row as a PAUSED probe — appends skip it until a
                    # heartbeat response resumes it.
                    r.prs.get_mut(i).paused = True
            in_config = conf.voters.contains(r.id)
            r.promotable = in_config
            if r.state != StateRole.Follower and not in_config:
                # Leader-step-down when the peer leaves the config: raw
                # role surgery exactly like the kernel — no
                # become_follower timer reset or timeout redraw.
                r.state = StateRole.Follower
                r.leader_id = 0
            elif r.state == StateRole.Leader:
                # Quorum-shrink commit pickup under the NEW config (the
                # reference's post_conf_change maybe_commit), without the
                # broadcast — the round's ordinary traffic propagates it.
                r.maybe_commit()

    def scheduled_round(self) -> None:
        """Advance one round: faults + eligibility + propose + gate +
        surgery, in exactly the device runner's order."""
        r = self.round_idx
        sch = self.schedule
        G, P = sch.n_groups, sch.n_peers
        if self.chaos is not None:
            link, crashed, capp = self.chaos.masks(r)
            append = sch.append[sch.phase_of_round[r]] + capp
        else:
            link = None
            crashed = np.zeros((P, G), dtype=bool)
            append = sch.append[sch.phase_of_round[r]]
        k = np.clip(self.op_ptr, 0, sch.op_start.shape[0] - 1)
        start = sch.op_start[k, np.arange(G)]
        active = (self.op_ptr < sch.n_ops) & (r >= start)
        want = active & (self.stage == 0)
        props = self.round(
            crashed=crashed.T, append_n=append, link=link,
            conf_propose=want,
        )
        for g in range(G):
            if want[g] and props[g][0] > 0:
                self.stage[g] = 1
                (
                    self.prop_owner[g],
                    self.prop_index[g],
                    self.prop_term[g],
                ) = props[g]
        for g in range(G):
            if self.stage[g] != 1:
                continue
            o = int(self.prop_owner[g])
            raft = self.cluster.networks[g].peers[o].raft
            own_lead = (
                raft.state == StateRole.Leader
                and raft.term == self.prop_term[g]
                and not crashed[o - 1, g]
            )
            if own_lead and raft.raft_log.committed >= self.prop_index[g]:
                self._apply_surgery(g, sch.slot(g, int(self.op_ptr[g])))
                self.op_ptr[g] += 1
                self.stage[g] = 0
            elif not own_lead:
                self.stage[g] = 0  # retry at the next acting leader
        self.round_idx += 1
