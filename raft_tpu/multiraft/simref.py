"""ScalarCluster: the lockstep parity oracle for ClusterSim.

Runs G groups × P real scalar `Raft` instances through the harness Network's
persist-before-send pump, one protocol round at a time, with the same
(node, term)-keyed deterministic timeouts as the device sim.  A round is:
tick every peer (in peer order) → pump to quiescence → propose the round's
append workload at the acting leader → pump.

Commit-index parity between this and ClusterSim on identical crash/append
schedules is THE correctness claim of the batched backend (BASELINE.json's
"bit-identical commit indices").
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..eraftpb import ConfState, Entry, Message, MessageType
from ..raft import StateRole
from ..raft_log import NO_LIMIT
from ..storage import MemStorage
from ..harness import Interface, Network


class ScalarCluster:
    def __init__(self, n_groups: int, n_peers: int, election_tick: int = 10,
                 heartbeat_tick: int = 1, voters=None, voters_outgoing=None,
                 learners=None, check_quorum: bool = False,
                 pre_vote: bool = False, metrics=None):
        """`voters`/`voters_outgoing`/`learners` (peer-id lists) bootstrap
        every group in that (possibly joint) configuration; default: all
        peers voters.  `check_quorum`/`pre_vote` configure every Raft the
        reference way (raft.rs Config); since ISSUE 7 the device sim
        models both (SimConfig.check_quorum / pre_vote route rounds
        through the damped wave path), so damped parity schedules set the
        SAME flags on both sides (tests/test_damping_parity.py) while the
        undamped suites keep both False.  `metrics` (an optional
        raft_tpu.metrics.Metrics) is shared by every Raft in the cluster —
        the scalar side of the device counter-plane parity test."""
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.networks: List[Network] = []
        for g in range(n_groups):
            config = Config(
                election_tick=election_tick,
                heartbeat_tick=heartbeat_tick,
                max_size_per_msg=NO_LIMIT,
                max_inflight_msgs=1 << 20,  # effectively unbounded window
                timeout_seed=g,
                check_quorum=check_quorum,
                pre_vote=pre_vote,
                metrics=metrics,
            )
            if voters is None:
                peers: List[Optional[Interface]] = [None] * n_peers
                self.networks.append(Network.new_with_config(peers, config))
            else:
                from ..raft import Raft

                ifaces = []
                for id in range(1, n_peers + 1):
                    cs = ConfState(
                        voters=list(voters),
                        voters_outgoing=list(voters_outgoing or []),
                        learners=list(learners or []),
                    )
                    store = MemStorage.new_with_conf_state(cs)
                    cfg = Config(**{**config.__dict__, "id": id})
                    ifaces.append(Interface(Raft(cfg, store)))
                self.networks.append(
                    Network.new_with_config(ifaces, config)
                )

    def _apply_crash_mask(
        self,
        net: Network,
        crashed_row: Sequence[bool],
        link_row: Optional[np.ndarray] = None,
    ) -> None:
        """Install the round's faults as per-edge drops: whole-peer crashes
        (isolation) plus, when a `link_row[P, P]` reachability matrix is
        given, a 1.0 drop on every down DIRECTED link — the scalar half of
        the chaos engine's link plane (sim.step's `link=`)."""
        net.recover()
        for p, c in enumerate(crashed_row):
            if c:
                net.isolate(p + 1)
        if link_row is not None:
            for a in range(self.n_peers):
                for b in range(self.n_peers):
                    if a != b and not link_row[a, b]:
                        net.drop(a + 1, b + 1, 1.0)

    def round(self, crashed: Optional[np.ndarray] = None,
              append_n: Optional[np.ndarray] = None,
              link: Optional[np.ndarray] = None) -> None:
        """One lockstep protocol round across all groups.

        crashed:  bool[G, P] whole-peer isolation for the round.
        append_n: int[G] workload proposed at each group's acting leader.
        link:     optional bool[P, P, G] directed reachability (peer-major
                  src/dst axes, like the device plane); a down link drops
                  every message on that edge for the whole round.
        """
        if crashed is None:
            crashed = np.zeros((self.n_groups, self.n_peers), dtype=bool)
        if append_n is None:
            append_n = np.zeros((self.n_groups,), dtype=np.int64)
        for g, net in enumerate(self.networks):
            self._apply_crash_mask(
                net, crashed[g], None if link is None else link[:, :, g]
            )
            # Tick every peer in peer order, collecting outbound messages
            # with the pump's persist-before-send discipline.
            initial: List[Message] = []
            for p in range(1, self.n_peers + 1):
                peer = net.peers[p]
                peer.raft.tick()
                peer.persist()
                initial.extend(net.filter(peer.read_messages()))
            net.send(initial)
            # Propose the append workload at the acting leader (the alive
            # leader with the highest term).
            n = int(append_n[g])
            if n > 0:
                lead = self.acting_leader(g, crashed[g])
                if lead is not None:
                    ents = [Entry(data=b"x") for _ in range(n)]
                    net.send([
                        Message(
                            msg_type=MessageType.MsgPropose,
                            from_=lead,
                            to=lead,
                            entries=ents,
                        )
                    ])

    def acting_leader(self, g: int, crashed_row: Sequence[bool]) -> Optional[int]:
        best = None
        best_term = -1
        for p in range(1, self.n_peers + 1):
            if crashed_row[p - 1]:
                continue
            r = self.networks[g].peers[p].raft
            if r.state == StateRole.Leader and r.term > best_term:
                best, best_term = p, r.term
        return best

    # --- state extraction for parity comparison ---

    def snapshot(self) -> dict:
        G, P = self.n_groups, self.n_peers
        out = {
            k: np.zeros((G, P), dtype=np.int64)
            for k in ("term", "state", "commit", "last_index", "last_term")
        }
        for g in range(G):
            for p in range(P):
                r = self.networks[g].peers[p + 1].raft
                out["term"][g, p] = r.term
                out["state"][g, p] = r.state
                out["commit"][g, p] = r.raft_log.committed
                out["last_index"][g, p] = r.raft_log.last_index()
                out["last_term"][g, p] = r.raft_log.last_term()
        return out


def host_pack_bits_g(plane: np.ndarray) -> np.ndarray:
    """Numpy twin of kernels.pack_bits_g: pack a bool plane 32:1 along its
    LAST (group) axis into uint32 words (word w's bit j = group 32*w + j,
    zero-padded past G).  The GC010 oracle for the recent_active
    scan-carry packing — tests/test_multiraft_kernels.py asserts bit-exact
    equality with the device kernel at awkward widths."""
    plane = np.asarray(plane, dtype=bool)
    g = plane.shape[-1]
    n_words = (g + 31) // 32
    pad = n_words * 32 - g
    bits = plane.astype(np.uint32)
    if pad:
        bits = np.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    bits = bits.reshape(plane.shape[:-1] + (n_words, 32))
    lanes = np.arange(32, dtype=np.uint32)
    return (bits << lanes).sum(axis=-1).astype(np.uint32)


def host_unpack_bits_g(words: np.ndarray, g: int) -> np.ndarray:
    """Numpy twin of kernels.unpack_bits_g (inverse of host_pack_bits_g)."""
    words = np.asarray(words, dtype=np.uint32)
    lanes = np.arange(32, dtype=np.uint32)
    bits = (words[..., :, None] >> lanes) & np.uint32(1)
    flat = bits.reshape(words.shape[:-1] + (words.shape[-1] * 32,))
    return flat[..., :g] != 0


class HealthOracle:
    """Scalar-side oracle for the device health planes (sim.HealthState).

    Maintains the same four per-group int32 planes — leaderless_ticks,
    ticks_since_commit, term_bumps_in_window, vote_splits (row order
    kernels.HP_*) — from OBSERVABLE scalar-cluster state, with the
    bit-identical fold rules of kernels.update_health:

      * has_leader:      some alive peer ends the round as Leader;
      * commit_advanced: the group's max commit index grew this round;
      * term_bump:       growth of the group's max term this round;
      * campaigned:      some peer's election timer fires this round —
                         computed BEFORE the round from the same facts as
                         kernels.tick_kernel (not-leader & promotable &
                         election_elapsed + 1 >= randomized timeout,
                         reference: raft.rs:1037-1047);
      * won:             some peer became leader during the round (Leader
                         at round end with a new term or a non-Leader
                         pre-round role — become_leader is the only path);
      * vote_split:      campaigned and nobody won.

    tests/test_health_parity.py asserts exact per-round equality of these
    planes against ClusterSim's device-maintained HealthState.

    This class is the resolved GC010 oracle symbol for the health kernels
    (tools/graftcheck/parity_obligations.json: zero_health/update_health
    -> simref.HealthOracle); renaming it or its `round` entry point is an
    obligation change and must go through `make obligations`.
    """

    def __init__(self, cluster: ScalarCluster, window: int = 32):
        self.cluster = cluster
        G = cluster.n_groups
        self.planes = np.zeros((4, G), dtype=np.int32)
        self.window = window
        self.window_pos = 0

    def _capture(self):
        G, P = self.cluster.n_groups, self.cluster.n_peers
        from ..raft import StateRole

        state = np.zeros((G, P), dtype=np.int64)
        term = np.zeros((G, P), dtype=np.int64)
        commit = np.zeros((G, P), dtype=np.int64)
        for g in range(G):
            for p in range(P):
                r = self.cluster.networks[g].peers[p + 1].raft
                state[g, p] = int(r.state)
                term[g, p] = r.term
                commit[g, p] = r.raft_log.committed
        return state, term, commit, int(StateRole.Leader)

    def round(self, crashed=None, append_n=None, link=None) -> None:
        """Drive one cluster round and fold its health facts into the
        planes (the scalar twin of sim.step's health extra).  `link` is
        the optional bool[P, P, G] chaos reachability plane, passed
        through to ScalarCluster.round."""
        G, P = self.cluster.n_groups, self.cluster.n_peers
        if crashed is None:
            crashed = np.zeros((G, P), dtype=bool)
        pre_state, pre_term, pre_commit, leader_code = self._capture()
        want_campaign = np.zeros((G, P), dtype=bool)
        for g in range(G):
            for p in range(P):
                r = self.cluster.networks[g].peers[p + 1].raft
                want_campaign[g, p] = (
                    int(r.state) != leader_code
                    and r.promotable
                    and r.election_elapsed + 1 >= r.randomized_election_timeout
                )

        self.cluster.round(crashed, append_n, link)

        post_state, post_term, post_commit, _ = self._capture()
        alive = ~np.asarray(crashed, dtype=bool)
        has_leader = np.any((post_state == leader_code) & alive, axis=1)
        commit_adv = post_commit.max(axis=1) > pre_commit.max(axis=1)
        term_bump = (post_term.max(axis=1) - pre_term.max(axis=1)).astype(
            np.int32
        )
        won = np.any(
            (post_state == leader_code)
            & ((pre_state != leader_code) | (post_term > pre_term)),
            axis=1,
        )
        campaigned = np.any(want_campaign, axis=1)

        leaderless, since, bumps, splits = self.planes
        leaderless = np.where(has_leader, 0, leaderless + 1)
        since = np.where(commit_adv, 0, since + 1)
        if self.window_pos == 0:
            bumps = np.zeros_like(bumps)
        bumps = bumps + term_bump
        splits = splits + (campaigned & ~won).astype(np.int32)
        self.planes = np.stack([leaderless, since, bumps, splits]).astype(
            np.int32
        )
        self.window_pos = (self.window_pos + 1) % self.window


class ChaosOracle(HealthOracle):
    """Scalar-side oracle for chaos (link-fault) schedules.

    Replays a compiled fault schedule (chaos.HostSchedule — the numpy twin
    of the device schedule arrays, including the bit-identical per-round
    loss draws) through real Raft state machines: each round installs the
    round's effective link matrix as per-edge 1.0 drops on the harness
    Network, runs the standard lockstep round, and folds the same health
    facts as HealthOracle.  tests/test_chaos_parity.py asserts exact
    per-round equality of every peer's state AND the health planes against
    ClusterSim stepping the identical schedule through the link-gated
    device path (sim.step's `link=`).

    This class is the resolved GC010 oracle symbol for the chaos kernels
    (tools/graftcheck/parity_obligations.json: link_loss_draw /
    check_safety -> simref.ChaosOracle); renaming it or its entry points
    is an obligation change and must go through `make obligations`.
    """

    def __init__(self, cluster: ScalarCluster, schedule=None, window: int = 32):
        super().__init__(cluster, window=window)
        self.schedule = schedule
        self.round_idx = 0

    def scheduled_round(self) -> None:
        """Advance one round of the attached chaos.HostSchedule."""
        if self.schedule is None:
            raise RuntimeError("no schedule attached; pass schedule= or "
                               "call round(link=...) directly")
        link, crashed, append = self.schedule.masks(self.round_idx)
        self.round_idx += 1
        # Schedule planes are peer-major [P, G]; the scalar round wants
        # [G, P] crash rows.
        self.round(crashed=crashed.T, append_n=append, link=link)
