"""ScalarCluster: the lockstep parity oracle for ClusterSim.

Runs G groups × P real scalar `Raft` instances through the harness Network's
persist-before-send pump, one protocol round at a time, with the same
(node, term)-keyed deterministic timeouts as the device sim.  A round is:
tick every peer (in peer order) → pump to quiescence → propose the round's
append workload at the acting leader → pump.

Commit-index parity between this and ClusterSim on identical crash/append
schedules is THE correctness claim of the batched backend (BASELINE.json's
"bit-identical commit indices").
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..eraftpb import ConfState, Entry, Message, MessageType
from ..raft import StateRole
from ..raft_log import NO_LIMIT
from ..storage import MemStorage
from ..harness import Interface, Network


class ScalarCluster:
    def __init__(self, n_groups: int, n_peers: int, election_tick: int = 10,
                 heartbeat_tick: int = 1, voters=None, voters_outgoing=None,
                 learners=None, check_quorum: bool = False,
                 pre_vote: bool = False, metrics=None):
        """`voters`/`voters_outgoing`/`learners` (peer-id lists) bootstrap
        every group in that (possibly joint) configuration; default: all
        peers voters.  `check_quorum`/`pre_vote` configure every Raft the
        reference way (raft.rs Config); the device sim models neither (the
        host path handles them — see sim.py's protocol-scope note), so
        parity schedules leave both False.  `metrics` (an optional
        raft_tpu.metrics.Metrics) is shared by every Raft in the cluster —
        the scalar side of the device counter-plane parity test."""
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.networks: List[Network] = []
        for g in range(n_groups):
            config = Config(
                election_tick=election_tick,
                heartbeat_tick=heartbeat_tick,
                max_size_per_msg=NO_LIMIT,
                max_inflight_msgs=1 << 20,  # effectively unbounded window
                timeout_seed=g,
                check_quorum=check_quorum,
                pre_vote=pre_vote,
                metrics=metrics,
            )
            if voters is None:
                peers: List[Optional[Interface]] = [None] * n_peers
                self.networks.append(Network.new_with_config(peers, config))
            else:
                from ..raft import Raft

                ifaces = []
                for id in range(1, n_peers + 1):
                    cs = ConfState(
                        voters=list(voters),
                        voters_outgoing=list(voters_outgoing or []),
                        learners=list(learners or []),
                    )
                    store = MemStorage.new_with_conf_state(cs)
                    cfg = Config(**{**config.__dict__, "id": id})
                    ifaces.append(Interface(Raft(cfg, store)))
                self.networks.append(
                    Network.new_with_config(ifaces, config)
                )

    def _apply_crash_mask(self, net: Network, crashed_row: Sequence[bool]) -> None:
        net.recover()
        for p, c in enumerate(crashed_row):
            if c:
                net.isolate(p + 1)

    def round(self, crashed: Optional[np.ndarray] = None,
              append_n: Optional[np.ndarray] = None) -> None:
        """One lockstep protocol round across all groups."""
        if crashed is None:
            crashed = np.zeros((self.n_groups, self.n_peers), dtype=bool)
        if append_n is None:
            append_n = np.zeros((self.n_groups,), dtype=np.int64)
        for g, net in enumerate(self.networks):
            self._apply_crash_mask(net, crashed[g])
            # Tick every peer in peer order, collecting outbound messages
            # with the pump's persist-before-send discipline.
            initial: List[Message] = []
            for p in range(1, self.n_peers + 1):
                peer = net.peers[p]
                peer.raft.tick()
                peer.persist()
                initial.extend(net.filter(peer.read_messages()))
            net.send(initial)
            # Propose the append workload at the acting leader (the alive
            # leader with the highest term).
            n = int(append_n[g])
            if n > 0:
                lead = self.acting_leader(g, crashed[g])
                if lead is not None:
                    ents = [Entry(data=b"x") for _ in range(n)]
                    net.send([
                        Message(
                            msg_type=MessageType.MsgPropose,
                            from_=lead,
                            to=lead,
                            entries=ents,
                        )
                    ])

    def acting_leader(self, g: int, crashed_row: Sequence[bool]) -> Optional[int]:
        best = None
        best_term = -1
        for p in range(1, self.n_peers + 1):
            if crashed_row[p - 1]:
                continue
            r = self.networks[g].peers[p].raft
            if r.state == StateRole.Leader and r.term > best_term:
                best, best_term = p, r.term
        return best

    # --- state extraction for parity comparison ---

    def snapshot(self) -> dict:
        G, P = self.n_groups, self.n_peers
        out = {
            k: np.zeros((G, P), dtype=np.int64)
            for k in ("term", "state", "commit", "last_index", "last_term")
        }
        for g in range(G):
            for p in range(P):
                r = self.networks[g].peers[p + 1].raft
                out["term"][g, p] = r.term
                out["state"][g, p] = r.state
                out["commit"][g, p] = r.raft_log.committed
                out["last_index"][g, p] = r.raft_log.last_index()
                out["last_term"][g, p] = r.raft_log.last_term()
        return out
