"""The plane registry: one declarative row per device-plane family.

Every device-resident plane the batched MultiRaft carries — the SimState
protocol planes, the BlackboxState flight-recorder ring, the counter /
health / read-stat accumulator slots, the packed-word encodings, and the
checkpoint-only carries (reconfig, read) — is described here ONCE, and
everything that used to hand-duplicate that knowledge derives from the
row instead:

  * ``checkpoint.py`` iterates ``checkpoint_fields(...)`` for its save /
    load field sets (required vs optional comes from the gating flag);
  * ``sharding.state_sharding`` / ``blackbox_sharding`` build their
    PartitionSpecs from ``shape`` + ``sharding``;
  * ``sim.pack_ra_carry`` packs the ``packing == "bits_g"`` rows for the
    donated scan carry;
  * ``pallas_step.steady_mask`` wholesale-defuses fused horizons for the
    ``steady == "defuse"`` rows' gating flags;
  * ``tools/graftcheck/engine/overflow.py`` imports the seven GC008
    registries (COUNTER/HEALTH/PACKED/DAMPING/TRANSFER/BLACKBOX/READ)
    from the module-bottom derivations instead of keeping local copies.

The loop is closed by graftcheck GC016 (registry-closure): the rule
proves both directions — every optional SimState/BlackboxState field,
checkpoint key, sharding entry, and steady-mask defuse condition
resolves to a row here, and every row is consumed by the five sites —
so a future plane (e.g. ROADMAP item 4's snapshot/compaction cursors)
lands as one PlaneSpec + one kernel + one oracle, and hand-written
bypass plumbing fails the build.

STDLIB-ONLY BY DESIGN: graftcheck loads this file standalone (by path,
without importing the jax-dependent package), so nothing here may import
jax, numpy, or any sibling module.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Set, Tuple


class PlaneSpec(NamedTuple):
    """One registry row.

    name:       the field / constant name at the owner site.
    owner:      where the plane lives — "SimState", "BlackboxState",
                "ReconfigState" (sim/reconfig NamedTuple fields),
                "kernels" (CTR_*/HP_* plane-stack slots and pack_*
                kernel families), "pallas_step" (builder-packed words),
                or "workload" (RS_* slots and the read carry).
    family:     which GC008 registry the row lands in — "core" (no
                overflow registry; the protocol planes), "counter",
                "health", "packed", "damping", "transfer", "blackbox",
                "read", "read-carry", "reconfig".
    shape:      shape family, written exactly as the GC007 anchor spells
                it: "[P, G]", "[P, P, G]", "[W, G]", "[S, G]", "[H, G]",
                "[C, G]", "[G]", "[R]", "[L]", "[]", or "word" (a packed
                sub-int32 lane encoding, not a standalone array).
    dtype:      the GC007 anchor dtype ("int32" / "bool" / "uint32");
                GC016 pins the owner field's ``# gc:`` anchor to
                ``dtype + shape``.
    flag:       gating SimConfig flags (ANY of them turns the plane on;
                empty = always present).  Presence gating implies the
                checkpoint treats the field as optional and the sharding
                spec is built only when a named flag is set.
    bound_bits: the GC008 numeric bound — bits per lane for packed /
                damping / transfer rows, max additive growth per round
                for health rows, None where the bound is structural
                (rings, carries) or lives in the derivation text.
    bound:      the overflow-bound derivation summary (the GC008
                registry value; docs/STATIC_ANALYSIS.md carries the full
                derivations).
    packing:    scan-carry packing policy — "bits_g" (rides the donated
                scan carry bit-packed 32:1 along G via
                kernels.pack_bits_g; consumed by sim.pack_ra_carry),
                "word" (a packed-word lane family), or "none".
    checkpoint: which checkpoint file persists the plane — "state"
                (SimState .npz; required unless flag-gated), "blackbox"
                (__blackbox_version__ sidecar), "read"
                (__read_version__), "reconfig" (__reconfig_version__),
                or "none".
    sharding:   mesh placement — "minor-G" (shard the trailing group
                axis, leading axes replicated), "replicate" (whole-array
                replica, e.g. scalars), or "none" (never placed).
    steady:     steady_mask interaction — "fusable" (no interaction),
                "defuse" (the gating flag wholesale-rejects fused
                horizons; consumed by steady_defuse_flags), or
                "predicate:<name>" (a per-group condition hand-derived
                in steady_mask; named so the docstring and this registry
                can be cross-read).
    oracle:     the scalar twin symbol ("module.Symbol" under
                raft_tpu/multiraft/) GC016 resolves, or None where the
                plane has no dedicated oracle beyond the ScalarCluster
                parity suites.
    """

    name: str
    owner: str
    family: str
    shape: str
    dtype: str
    flag: Tuple[str, ...] = ()
    bound_bits: Optional[int] = None
    bound: str = ""
    packing: str = "none"
    checkpoint: str = "none"
    sharding: str = "none"
    steady: str = "fusable"
    oracle: Optional[str] = None


# Declared per-round per-counter event budget: the `256` in ClusterSim's
# _drain_cap expression.  events/window <= window * BUDGET_PER_GROUP * G.
BUDGET_PER_GROUP = 256
# int32 wrap exponent: windows must keep total events <= 2**31.
WRAP_SHIFT = 31

# Names inside kernels.update_health whose values are DECLARED bounded
# (<= bound) with the derivation documented in docs/STATIC_ANALYSIS.md
# rather than proven from the AST.  term_bump: a group's max term grows
# by at most 1 per round (each campaigner adds exactly 1 to its own term
# and every bump target adopts an existing campaigner's term).
DECLARED_BOUNDED: Dict[str, int] = {"term_bump": 1}


def _sim(name: str, shape: str, dtype: str = "int32", **kw) -> PlaneSpec:
    kw.setdefault("family", "core")
    kw.setdefault("checkpoint", "state")
    kw.setdefault("sharding", "minor-G")
    return PlaneSpec(name=name, owner="SimState", shape=shape, dtype=dtype, **kw)


REGISTRY: Tuple[PlaneSpec, ...] = (
    # ---- SimState protocol planes, in FIELD ORDER (GC016 pins the order
    # against the NamedTuple so checkpoint/sharding iteration is the
    # field iteration).
    _sim("term", "[P, G]"),
    _sim("state", "[P, G]"),
    _sim("vote", "[P, G]"),
    _sim("leader_id", "[P, G]"),
    _sim(
        "election_elapsed", "[P, G]", family="damping", bound_bits=8,
        bound=(
            "lease operand: < election_tick at leaders (boundary reset); "
            "< 2*election_tick at followers (timeout redraw bound)"
        ),
    ),
    _sim("heartbeat_elapsed", "[P, G]"),
    _sim("randomized_timeout", "[P, G]"),
    _sim("last_index", "[P, G]"),
    _sim("last_term", "[P, G]"),
    _sim("commit", "[P, G]"),
    _sim("matched", "[P, P, G]"),
    _sim("term_start_index", "[P, G]"),
    _sim("agree", "[P, P, G]"),
    _sim("voter_mask", "[P, G]", dtype="bool"),
    _sim("outgoing_mask", "[P, G]", dtype="bool"),
    _sim("learner_mask", "[P, G]", dtype="bool"),
    _sim(
        "recent_active", "[P, P, G]", dtype="bool", family="damping",
        flag=("check_quorum", "pre_vote"), bound_bits=1,
        bound="bool; boundary read-and-clear + won reset",
        packing="bits_g", steady="predicate:cq-boundary-safe",
    ),
    _sim(
        "transferee", "[P, G]", family="transfer", flag=("transfer",),
        bound_bits=4,
        bound=(
            "peer id in [0, n_peers]; set from validated commands "
            "(kernels.apply_transfer) or cleared, never arithmetic"
        ),
        steady="predicate:transfer-pending",
        oracle="simref.TransferOracle",
    ),
    # ---- BlackboxState flight-recorder planes (ISSUE 15), in FIELD
    # ORDER (the checkpoint's save order).
    PlaneSpec(
        "meta", "BlackboxState", "blackbox", "[W, G]", "uint32",
        flag=("blackbox",),
        bound=(
            "ring slot, overwritten every W rounds (no accumulation); "
            "word bits bounded by PACKED_PLANES `blackbox_meta`"
        ),
        checkpoint="blackbox", sharding="minor-G", steady="defuse",
        oracle="forensics.decode_window",
    ),
    PlaneSpec(
        "term", "BlackboxState", "blackbox", "[W, G]", "int32",
        flag=("blackbox",),
        bound=(
            "ring slot of group max term (bounded by the protocol's own "
            "int32 term plane)"
        ),
        checkpoint="blackbox", sharding="minor-G", steady="defuse",
        oracle="forensics.decode_window",
    ),
    PlaneSpec(
        "commit", "BlackboxState", "blackbox", "[W, G]", "int32",
        flag=("blackbox",),
        bound=(
            "ring slot of group max commit (bounded by the int32 "
            "commit plane)"
        ),
        checkpoint="blackbox", sharding="minor-G", steady="defuse",
        oracle="forensics.decode_window",
    ),
    PlaneSpec(
        "trip_round", "BlackboxState", "blackbox", "[S, G]", "int32",
        flag=("blackbox",),
        bound="min-fold of round indices < compiled horizon < 2**31",
        checkpoint="blackbox", sharding="minor-G", steady="defuse",
        oracle="forensics.decode_window",
    ),
    PlaneSpec(
        "round_idx", "BlackboxState", "blackbox", "[]", "int32",
        flag=("blackbox",),
        bound="+1/round; wrap horizon 2**31 rounds, out of model",
        checkpoint="blackbox", sharding="replicate", steady="defuse",
    ),
    # ---- Counter plane slots (kernels.CTR_*): <= BUDGET_PER_GROUP
    # events/group/round, drained inside the _drain_cap window bound.
    PlaneSpec(
        "CTR_CAMPAIGNS", "kernels", "counter", "[C, G]", "int32",
        bound="<= BUDGET_PER_GROUP events/group/round; window-drained",
    ),
    PlaneSpec(
        "CTR_HEARTBEATS", "kernels", "counter", "[C, G]", "int32",
        bound="<= BUDGET_PER_GROUP events/group/round; window-drained",
    ),
    PlaneSpec(
        "CTR_ELECTIONS_WON", "kernels", "counter", "[C, G]", "int32",
        bound="<= BUDGET_PER_GROUP events/group/round; window-drained",
    ),
    PlaneSpec(
        "CTR_COMMIT_ENTRIES", "kernels", "counter", "[C, G]", "int32",
        bound="<= BUDGET_PER_GROUP events/group/round; window-drained",
    ),
    # ---- Health plane slots (kernels.HP_*): bound_bits is the max
    # additive growth per round (resets only shrink), giving a wrap
    # horizon of 2**31 rounds — out of model, like the commit plane.
    PlaneSpec(
        "HP_LEADERLESS", "kernels", "health", "[H, G]", "int32",
        bound_bits=1, bound="+1/round max; reset on a led round",
    ),
    PlaneSpec(
        "HP_SINCE_COMMIT", "kernels", "health", "[H, G]", "int32",
        bound_bits=1, bound="+1/round max; reset on commit advance",
    ),
    PlaneSpec(
        "HP_TERM_BUMPS", "kernels", "health", "[H, G]", "int32",
        bound_bits=1, bound="+term_bump (declared <= 1); window reset",
    ),
    PlaneSpec(
        "HP_VOTE_SPLITS", "kernels", "health", "[H, G]", "int32",
        bound_bits=1, bound="+1/round max; reset on election outcome",
    ),
    # ---- Packed-word lane families (GC008 PACKED_PLANES): every
    # sub-int32 value riding a shared word, with its bit budget.
    PlaneSpec(
        "bits", "kernels", "packed", "word", "int32", bound_bits=1,
        bound="bool planes; lossless by construction", packing="word",
    ),
    PlaneSpec(
        "u16_pairs", "kernels", "packed", "word", "int32", bound_bits=16,
        bound="loss rates <= LOSS_SCALE (chaos._rate_to_fp)",
        packing="word",
    ),
    PlaneSpec(
        "bits_g", "kernels", "packed", "word", "int32", bound_bits=1,
        bound="bool planes packed along G; lossless by construction",
        packing="word", oracle="simref.host_pack_bits_g",
    ),
    PlaneSpec(
        "roles", "pallas_step", "packed", "word", "int32", bound_bits=30,
        bound="state<4, leader_id<16, hb<=heartbeat_tick<2**24",
        packing="word",
    ),
    PlaneSpec(
        "masks", "pallas_step", "packed", "word", "int32", bound_bits=3,
        bound="three bool planes", packing="word",
    ),
    PlaneSpec(
        "blackbox_meta", "kernels", "packed", "word", "uint32",
        bound_bits=15,
        bound="role<4, leader_id<=n_peers<16, N_SAFETY=9 violation bits",
        packing="word",
    ),
    # ---- Read-stat slots (workload.RS_*, GC008 READ_PLANES): every slot
    # grows by at most G per round; workload._compile_arrays asserts
    # rounds x G < 2**31 at compile time.
    PlaneSpec(
        "RS_ISSUED", "workload", "read", "[R]", "int32",
        bound="<= G fresh reads per round", oracle="simref.ReadOracle",
    ),
    PlaneSpec(
        "RS_SERVED_LEASE", "workload", "read", "[R]", "int32",
        bound="<= G lease serves per round", oracle="simref.ReadOracle",
    ),
    PlaneSpec(
        "RS_SERVED_QUORUM", "workload", "read", "[R]", "int32",
        bound="<= G quorum serves per round", oracle="simref.ReadOracle",
    ),
    PlaneSpec(
        "RS_DEGRADED_SERVES", "workload", "read", "[R]", "int32",
        bound="<= G degraded serves per round", oracle="simref.ReadOracle",
    ),
    PlaneSpec(
        "RS_RETRY_ROUNDS", "workload", "read", "[R]", "int32",
        bound="<= G outstanding (group, round) pairs per round",
        oracle="simref.ReadOracle",
    ),
    PlaneSpec(
        "RS_DROPPED_FIRES", "workload", "read", "[R]", "int32",
        bound="<= G dropped fires per round", oracle="simref.ReadOracle",
    ),
    # ---- Read-protocol checkpoint carry (checkpoint.save_read_state
    # order): the outstanding-read carry planes plus the run accumulators.
    PlaneSpec(
        "pending_mode", "workload", "read-carry", "[G]", "int32",
        bound="sim.READ_* codes (<= 2)", checkpoint="read",
        sharding="minor-G",
    ),
    PlaneSpec(
        "pending_since", "workload", "read-carry", "[G]", "int32",
        bound="absolute round index < n_rounds < 2**31 (compile bound)",
        checkpoint="read", sharding="minor-G",
    ),
    PlaneSpec(
        "read_stats", "workload", "read-carry", "[R]", "int32",
        bound="slot growth per READ_PLANES; rounds x G < 2**31",
        checkpoint="read", sharding="replicate",
    ),
    PlaneSpec(
        "lat_hist", "workload", "read-carry", "[L]", "int32",
        bound="<= G serves per round per bucket; rounds x G < 2**31",
        checkpoint="read", sharding="replicate",
    ),
    # ---- Reconfig op-protocol carry (reconfig.ReconfigState, in FIELD
    # ORDER — the checkpoint's save order).
    PlaneSpec(
        "stage", "ReconfigState", "reconfig", "[G]", "int32",
        bound="stage code in {0, 1}", checkpoint="reconfig",
        sharding="minor-G",
    ),
    PlaneSpec(
        "op_ptr", "ReconfigState", "reconfig", "[G]", "int32",
        bound="op-chain cursor <= plan ops per group", checkpoint="reconfig",
        sharding="minor-G",
    ),
    PlaneSpec(
        "prop_owner", "ReconfigState", "reconfig", "[G]", "int32",
        bound="peer id in [0, n_peers]", checkpoint="reconfig",
        sharding="minor-G",
    ),
    PlaneSpec(
        "prop_index", "ReconfigState", "reconfig", "[G]", "int32",
        bound="log index (bounded by the int32 last_index plane)",
        checkpoint="reconfig", sharding="minor-G",
    ),
    PlaneSpec(
        "prop_term", "ReconfigState", "reconfig", "[G]", "int32",
        bound="term (bounded by the int32 term plane)",
        checkpoint="reconfig", sharding="minor-G",
    ),
    PlaneSpec(
        "prev_voter", "ReconfigState", "reconfig", "[P, G]", "bool",
        bound="bool mask snapshot", checkpoint="reconfig",
        sharding="minor-G",
    ),
    PlaneSpec(
        "prev_outgoing", "ReconfigState", "reconfig", "[P, G]", "bool",
        bound="bool mask snapshot", checkpoint="reconfig",
        sharding="minor-G",
    ),
)


# --- accessors (the five consumer sites go through these) -------------------


def rows(
    owner: Optional[str] = None, family: Optional[str] = None
) -> Tuple[PlaneSpec, ...]:
    """Registry rows filtered by owner and/or family, in registry order."""
    return tuple(
        r
        for r in REGISTRY
        if (owner is None or r.owner == owner)
        and (family is None or r.family == family)
    )


def row(owner: str, name: str) -> PlaneSpec:
    for r in REGISTRY:
        if r.owner == owner and r.name == name:
            return r
    raise KeyError(f"no registry row for {owner}.{name}")


def sim_state_fields() -> Tuple[str, ...]:
    """SimState field names in registry (== NamedTuple) order."""
    return tuple(r.name for r in rows(owner="SimState"))


def optional_sim_fields() -> Tuple[str, ...]:
    """Flag-gated SimState fields: None when their flag is off, so both
    the checkpoint and the sharding spec treat them as optional."""
    return tuple(r.name for r in rows(owner="SimState") if r.flag)


def checkpoint_fields(policy: str) -> Tuple[str, ...]:
    """Field names persisted by the `policy` checkpoint file, in save
    order ("state" / "blackbox" / "read" / "reconfig")."""
    return tuple(r.name for r in REGISTRY if r.checkpoint == policy)


def packed_carry_fields() -> Tuple[str, ...]:
    """SimState fields that ride the donated scan carry bit-packed along
    the group axis (sim.pack_ra_carry / unpack_ra_carry)."""
    return tuple(
        r.name for r in rows(owner="SimState") if r.packing == "bits_g"
    )


def steady_defuse_flags() -> Tuple[str, ...]:
    """SimConfig flags whose planes wholesale-reject fused horizons
    (pallas_step.steady_mask returns all-False when any is set)."""
    out = []
    for r in REGISTRY:
        if r.steady == "defuse":
            for f in r.flag:
                if f not in out:
                    out.append(f)
    return tuple(out)


def gating_flags() -> Tuple[str, ...]:
    """Every SimConfig flag named by a registry row (GC016 checks each
    exists as a SimConfig field)."""
    out = []
    for r in REGISTRY:
        for f in r.flag:
            if f not in out:
                out.append(f)
    return tuple(out)


def leading_axes(r: PlaneSpec) -> int:
    """Number of leading (non-group, replicated) axes for a "minor-G"
    sharded row: "[P, G]" -> 1, "[P, P, G]" -> 2, "[G]" -> 0."""
    return r.shape.count(",")


# --- the seven GC008 registries, derived ------------------------------------
# (tools/graftcheck/engine/overflow.py imports these; GC016 fails the
# build if overflow.py regrows local copies.)

COUNTER_PLANES: Set[str] = {r.name for r in rows(family="counter")}

HEALTH_PLANES: Dict[str, int] = {
    r.name: r.bound_bits for r in rows(family="health")
}

PACKED_PLANES: Dict[str, tuple] = {
    r.name: (r.bound_bits, r.bound) for r in rows(family="packed")
}

DAMPING_PLANES: Dict[str, tuple] = {
    r.name: (r.bound_bits, r.bound) for r in rows(family="damping")
}

TRANSFER_PLANES: Dict[str, tuple] = {
    r.name: (r.bound_bits, r.bound) for r in rows(family="transfer")
}

BLACKBOX_PLANES: Dict[str, str] = {
    r.name: r.bound for r in rows(owner="BlackboxState")
}

READ_PLANES: Dict[str, str] = {r.name: r.bound for r in rows(family="read")}
