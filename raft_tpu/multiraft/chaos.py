"""Device-resident chaos engine: declarative fault plans compiled into
on-device schedules for the batched sim.

The fault surface is the pairwise link plane `link[P, P, G]` threaded
through ``sim.step`` (see ``sim._linked_step``): a whole-peer crash is the
special case ``link[p, :, g] = link[:, p, g] = False``, an asymmetric
partition is a directed subset, and per-link message loss is a seeded
per-round draw (``kernels.link_loss_draw``, keyed ``(round, src, dst,
group)`` so every run replays bit-exactly).

A :class:`ChaosPlan` is a list of phases — partitions, directed link
overrides, loss rates, crashes, heals — each covering a round range and an
optional group selector.  :func:`compile_plan` lowers it host-side into
dense per-phase schedule arrays; :func:`run_plan` then executes the whole
multi-phase scenario inside ONE jitted ``lax.scan`` with zero host round
trips: per-round masks are gathered from the schedule by phase index, the
loss plane is drawn on device, the link-gated step advances every group,
``kernels.check_safety`` folds the safety invariants (election safety,
committed-prefix agreement, commit monotonicity) into a violation
accumulator, and the health planes feed a time-to-reelect / MTTR
accumulator (``health.chaos_report`` formats the host-side summary).

Plan JSON (see docs/OBSERVABILITY.md "Chaos" and tests/testdata/chaos/)::

    {"name": "split-brain", "peers": 5, "phases": [
        {"rounds": 30},                                   # settle
        {"rounds": 40, "partition": [[1, 2], [3, 4, 5]],  # symmetric split
         "append": 1},
        {"rounds": 20, "links": [{"from": 1, "to": 2, "up": false}],
         "loss": [{"from": 3, "to": 4, "rate": 0.5}],
         "crash": [5], "groups": {"mod": 2, "eq": 0}},
        {"rounds": 30, "heal": true}]}

The scalar twin is ``simref.ChaosOracle``: it replays the SAME compiled
schedule through real Raft state machines and the harness Network's
per-edge drops — :func:`host_masks` / :func:`host_loss_draw` are the numpy
mirrors of the device schedule and must stay bit-identical
(tests/test_chaos_parity.py).

Since the runner-registry refactor the compiled runner is BUILT by the
unified factory (raft_tpu/multiraft/runner.py) from the schedules.py
registry row set; :func:`make_runner` here is a thin behavior-neutral
wrapper (GC018 machine-checks the closure, GC014 pins the jaxpr).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import kernels
from . import sim as sim_mod


# Group selectors: "all", an explicit id list, or {"mod": m, "eq": r}.
GroupSel = Union[str, Sequence[int], Dict[str, int]]


@dataclass
class ChaosPhase:
    """One contiguous stretch of rounds with a fixed fault topology.

    rounds:    phase length in protocol rounds (>= 1).
    partition: list of peer-id cells; links BETWEEN cells are down, links
               within a cell stay up.  Peers in no cell form one implicit
               extra cell.  None = no partition.
    links:     directed overrides [{"from": a, "to": b, "up": bool}],
               applied after the partition.
    loss:      directed loss rates [{"from": a, "to": b, "rate": 0..1}];
               "rate" is sampled per (round, link, group).
    loss_all:  uniform loss rate applied to every directed link first.
    crash:     peer ids crashed (fully isolated) for the phase.
    groups:    which groups the phase's faults apply to; non-selected
               groups run fault-free for the phase.
    append:    per-round append workload proposed at each group's leader.
    """

    rounds: int
    partition: Optional[List[List[int]]] = None
    links: List[Dict[str, object]] = field(default_factory=list)
    loss: List[Dict[str, object]] = field(default_factory=list)
    loss_all: float = 0.0
    crash: List[int] = field(default_factory=list)
    groups: GroupSel = "all"
    append: int = 0


@dataclass
class ChaosPlan:
    """A named multi-phase fault scenario (host-side, declarative)."""

    name: str
    n_peers: int
    phases: List[ChaosPhase]

    @property
    def n_rounds(self) -> int:
        return sum(ph.rounds for ph in self.phases)


def plan_from_dict(doc: Dict[str, object]) -> ChaosPlan:
    """Build a ChaosPlan from its JSON document form (see module doc)."""
    phases: List[ChaosPhase] = []
    for ph in doc["phases"]:  # type: ignore[index]
        if not isinstance(ph, dict):
            raise ValueError(f"phase is not an object: {ph!r}")
        if ph.get("heal"):
            ph = {"rounds": ph["rounds"], "append": ph.get("append", 0)}
        phases.append(
            ChaosPhase(
                rounds=int(ph["rounds"]),  # type: ignore[arg-type]
                partition=ph.get("partition"),  # type: ignore[arg-type]
                links=list(ph.get("links", [])),  # type: ignore[arg-type]
                loss=list(ph.get("loss", [])),  # type: ignore[arg-type]
                loss_all=float(ph.get("loss_all", 0.0)),  # type: ignore[arg-type]
                crash=[int(p) for p in ph.get("crash", [])],  # type: ignore[union-attr]
                groups=ph.get("groups", "all"),  # type: ignore[arg-type]
                append=int(ph.get("append", 0)),  # type: ignore[arg-type]
            )
        )
    return ChaosPlan(
        name=str(doc.get("name", "unnamed")),
        n_peers=int(doc["peers"]),  # type: ignore[arg-type]
        phases=phases,
    )


def load_plan(path: str) -> ChaosPlan:
    """Load a ChaosPlan from a JSON file (the bench.py --chaos input)."""
    with open(path, "r", encoding="utf-8") as f:
        return plan_from_dict(json.load(f))


def _group_mask(sel: GroupSel, n_groups: int) -> np.ndarray:
    if isinstance(sel, str):
        if sel != "all":
            raise ValueError(f"unknown group selector {sel!r}")
        return np.ones(n_groups, dtype=bool)
    if isinstance(sel, dict):
        m, r = int(sel["mod"]), int(sel["eq"])
        return (np.arange(n_groups) % m) == r
    mask = np.zeros(n_groups, dtype=bool)
    for g in sel:
        if not 0 <= int(g) < n_groups:
            raise ValueError(
                f"group id {g} out of range [0, {n_groups})"
            )
        mask[int(g)] = True
    return mask


def _peer_index(pid: object, n_peers: int, what: str, phase: int) -> int:
    """Validate a 1-based peer id from a plan document -> 0-based index
    (a 0 or negative id would otherwise silently wrap into the wrong
    peer's link row)."""
    p = int(pid)  # type: ignore[call-overload]
    if not 1 <= p <= n_peers:
        raise ValueError(
            f"phase {phase}: {what} peer id {p} out of range [1, {n_peers}]"
        )
    return p - 1


def _rate_to_fp(rate: float) -> int:
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"loss rate {rate} outside [0, 1]")
    return int(round(rate * kernels.LOSS_SCALE))


class CompiledChaos(NamedTuple):
    """Device schedule arrays for one plan at one batch shape.

    The bool/sub-int32 planes are stored PACKED (kernels.pack_bits /
    pack_u16_pairs — GC008 PACKED_PLANES): the per-round schedule gather
    in the jitted scan reads the packed words from HBM and unpacks them
    with a handful of VPU shift/mask ops, so the hot loop's schedule
    traffic shrinks ~6x at P = 5 (byte-per-bool [P, P, G] planes become
    ceil(P*P/32) uint32 words per group).  schedule_masks returns the
    planes UNPACKED — the step sees bit-identical masks either way
    (pinned by tests/test_chaos_parity.py's run_plan-vs-stepping case).

    phase_of_round: int32[R]                round -> phase index
    link_packed:    uint32[NPH, Wl, G]      per-phase base link plane,
                                            bit (s*P + d) of the word
                                            stack (Wl = ceil(P*P/32))
    loss_packed:    uint32[NPH, Wr, G]      per-phase loss rates
                                            (1/LOSS_SCALE <= 2**16, two
                                            halfwords per word, Wr =
                                            ceil(P*P/2))
    crashed_packed: uint32[NPH, 1, G]       per-phase crash masks, bit p
    append:         int32[NPH, G]           per-phase append workload
    n_peers:        static python int, the unpack shape
    """

    phase_of_round: jnp.ndarray  # gc: int32[R]
    link_packed: jnp.ndarray  # gc: uint32[NPH, WL, G]
    loss_packed: jnp.ndarray  # gc: uint32[NPH, WR, G]
    crashed_packed: jnp.ndarray  # gc: uint32[NPH, 1, G]
    append: jnp.ndarray  # gc: int32[NPH, G]
    n_peers: int

    @property
    def n_rounds(self) -> int:
        return int(self.phase_of_round.shape[0])


def _compile_arrays(
    plan: ChaosPlan, n_groups: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The numpy schedule (shared by the device path and the oracle)."""
    P, G = plan.n_peers, n_groups
    nph = len(plan.phases)
    if nph == 0:
        raise ValueError("plan has no phases")
    phase_of_round = np.zeros(plan.n_rounds, dtype=np.int32)
    link = np.ones((nph, P, P, G), dtype=bool)
    loss = np.zeros((nph, P, P, G), dtype=np.int32)
    crashed = np.zeros((nph, P, G), dtype=bool)
    append = np.zeros((nph, G), dtype=np.int32)
    r0 = 0
    for i, ph in enumerate(plan.phases):
        if ph.rounds < 1:
            raise ValueError(f"phase {i}: rounds must be >= 1")
        phase_of_round[r0 : r0 + ph.rounds] = i
        r0 += ph.rounds
        gsel = _group_mask(ph.groups, G)
        lk = np.ones((P, P), dtype=bool)
        if ph.partition is not None:
            cell = np.full(P, -1, dtype=np.int64)
            for c, ids in enumerate(ph.partition):
                for pid in ids:
                    cell[_peer_index(pid, P, "partition", i)] = c
            cell[cell < 0] = len(ph.partition)  # implicit last cell
            lk = cell[:, None] == cell[None, :]
        for ov in ph.links:
            a = _peer_index(ov["from"], P, "link", i)
            b = _peer_index(ov["to"], P, "link", i)
            lk[a, b] = bool(ov.get("up", False))
        ls = np.full((P, P), _rate_to_fp(ph.loss_all), dtype=np.int32)
        for ov in ph.loss:
            a = _peer_index(ov["from"], P, "loss", i)
            b = _peer_index(ov["to"], P, "loss", i)
            ls[a, b] = _rate_to_fp(float(ov["rate"]))  # type: ignore[arg-type]
        link[i] = np.where(gsel[None, None, :], lk[:, :, None], True)
        loss[i] = np.where(gsel[None, None, :], ls[:, :, None], 0)
        for pid in ph.crash:
            crashed[i, _peer_index(pid, P, "crash", i)] = gsel
        append[i] = np.where(gsel, ph.append, 0)
    # The chaos-stats accumulator sums per-group indicators over the run in
    # int32 (see run_plan); bound the schedule so it provably cannot wrap
    # (the GC008 discipline, derived in docs/STATIC_ANALYSIS.md).
    if plan.n_rounds * max(1, G) >= 2**31:
        raise ValueError(
            f"plan spans {plan.n_rounds} rounds x {G} groups >= 2**31 "
            "(group, round) pairs; the int32 chaos-stats accumulator "
            "could wrap — split the plan"
        )
    return phase_of_round, link, loss, crashed, append


def compile_plan(plan: ChaosPlan, n_groups: int) -> CompiledChaos:
    """Lower a ChaosPlan to device schedule arrays for `n_groups` groups
    (bool/loss planes packed — see CompiledChaos)."""
    phase_of_round, link, loss, crashed, append = _compile_arrays(
        plan, n_groups
    )
    P, G = plan.n_peers, n_groups
    nph = link.shape[0]
    return CompiledChaos(
        phase_of_round=jnp.asarray(phase_of_round, dtype=jnp.int32),
        link_packed=kernels.pack_bits(
            jnp.asarray(link, dtype=bool).reshape(nph, P * P, G).swapaxes(
                0, 1
            )
        ).swapaxes(0, 1),
        loss_packed=kernels.pack_u16_pairs(
            jnp.asarray(loss, dtype=jnp.int32).reshape(nph, P * P, G).swapaxes(
                0, 1
            )
        ).swapaxes(0, 1),
        crashed_packed=kernels.pack_bits(
            jnp.asarray(crashed, dtype=bool).swapaxes(0, 1)
        ).swapaxes(0, 1),
        append=jnp.asarray(append, dtype=jnp.int32),
        n_peers=P,
    )


def schedule_planes(
    compiled: CompiledChaos,
    round_idx: jnp.ndarray,  # gc: int32[]
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Device-side (base_link, loss_rate, crashed, append) for one round:
    the round's phase row gathered and unpacked WITHOUT the loss sample
    knocked out.  schedule_masks is the per-round consumer; the split
    fused dispatch (reconfig.make_split_runner) needs the base plane for
    its steady predicate and the raw rates for the in-kernel draw — both
    constant across a phase, so one gather covers a whole fused block."""
    P = compiled.n_peers
    G = compiled.append.shape[1]
    ph = compiled.phase_of_round[round_idx]
    link = kernels.unpack_bits(compiled.link_packed[ph], P * P).reshape(
        P, P, G
    )
    loss = kernels.unpack_u16_pairs(compiled.loss_packed[ph], P * P).reshape(
        P, P, G
    )
    crashed = kernels.unpack_bits(compiled.crashed_packed[ph], P)
    return link, loss, crashed, compiled.append[ph]


def schedule_masks(
    compiled: CompiledChaos,
    round_idx: jnp.ndarray,  # gc: int32[]
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Device-side (link, crashed, append) for one round of the schedule:
    gather the round's (packed) phase row, unpack it on device, and knock
    out the seeded loss sample."""
    link, loss, crashed, append = schedule_planes(compiled, round_idx)
    drop = kernels.link_loss_draw(round_idx, loss)
    return link & ~drop, crashed, append


# --- host twins (the ChaosOracle side; must stay bit-identical) -----------


def host_loss_draw(round_idx: int, loss_rate: np.ndarray) -> np.ndarray:
    """Numpy twin of kernels.link_loss_draw (same counter PRNG, same key
    layout); tests/test_chaos_parity.py pins bit-equality."""
    P = loss_rate.shape[0]
    G = loss_rate.shape[2]
    g = np.arange(G, dtype=np.uint32)[None, None, :]
    s = np.arange(P, dtype=np.uint32)[:, None, None]
    d = np.arange(P, dtype=np.uint32)[None, :, None]
    lane = s * np.uint32(P) + d + np.uint32(1)

    def mix(x: np.ndarray) -> np.ndarray:
        x = x.astype(np.uint32)
        x ^= x >> np.uint32(16)
        x = (x * np.uint32(0x85EBCA6B)).astype(np.uint32)
        x ^= x >> np.uint32(13)
        x = (x * np.uint32(0xC2B2AE35)).astype(np.uint32)
        x ^= x >> np.uint32(16)
        return x

    x = mix(
        (g * np.uint32(0x9E3779B1) + np.uint32(round_idx)).astype(np.uint32)
    )
    x = mix(x ^ (lane * np.uint32(0x85EBCA6B)).astype(np.uint32))
    return (x % np.uint32(kernels.LOSS_SCALE)).astype(np.int32) < loss_rate


class HostSchedule:
    """The compiled schedule kept in numpy — what simref.ChaosOracle walks.

    Round r's effective masks are exactly what schedule_masks hands the
    device step: base link plane of the round's phase, minus the seeded
    loss sample, plus the phase crash mask and append workload.
    """

    def __init__(self, plan: ChaosPlan, n_groups: int):
        (
            self.phase_of_round,
            self.link,
            self.loss,
            self.crashed,
            self.append,
        ) = _compile_arrays(plan, n_groups)
        self.n_rounds = plan.n_rounds
        self.n_peers = plan.n_peers
        self.n_groups = n_groups

    def masks(
        self, round_idx: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(link[P, P, G], crashed[P, G], append[G]) for one round."""
        ph = int(self.phase_of_round[round_idx])
        drop = host_loss_draw(round_idx, self.loss[ph])
        return self.link[ph] & ~drop, self.crashed[ph], self.append[ph]


# --- the compiled-run harness ---------------------------------------------

# Chaos-stats accumulator indices ([N_CHAOS_STATS] int32; time-to-reelect /
# MTTR off the PR 3 health planes — health.chaos_report formats them).
CS_REELECTIONS = 0  # leaderless episodes that ended (leader regained)
CS_HEALED_ROUNDS = 1  # summed length of ended episodes (MTTR numerator)
CS_MAX_STREAK = 2  # longest leaderless streak observed anywhere
CS_LEADERLESS_ROUNDS = 3  # total leaderless (group, round) pairs
N_CHAOS_STATS = 4

CHAOS_STAT_NAMES = (
    "reelections",
    "healed_rounds",
    "max_leaderless_streak",
    "leaderless_group_rounds",
)


def update_chaos_stats(
    stats: jnp.ndarray,  # gc: int32[S]
    prev_leaderless: jnp.ndarray,  # gc: int32[G]
    new_leaderless: jnp.ndarray,  # gc: int32[G]
) -> jnp.ndarray:
    """Fold one round's leaderless-plane transition into the stats."""
    healed = (prev_leaderless > 0) & (new_leaderless == 0)
    # dtype= on the sums: bare reductions widen to int64 under x64 (GC007).
    delta = jnp.stack(
        [
            jnp.sum(healed, dtype=jnp.int32),
            jnp.sum(jnp.where(healed, prev_leaderless, 0), dtype=jnp.int32),
            jnp.int32(0),
            jnp.sum(new_leaderless > 0, dtype=jnp.int32),
        ]
    )
    out = stats + delta
    return out.at[CS_MAX_STREAK].set(
        jnp.maximum(stats[CS_MAX_STREAK], jnp.max(new_leaderless))
    )


def make_runner(cfg: sim_mod.SimConfig, compiled: CompiledChaos):
    """Build the jitted whole-scenario runner: one lax.scan over every
    round of the compiled schedule with zero host round trips inside —
    per-round masks gathered on device, the link-gated step, the safety
    fold, and the MTTR stats fold all fuse into the scan body.

    The schedule arrays enter the jit as RUNTIME ARGUMENTS, not closure
    captures: a closed-over schedule is baked into the jaxpr as consts
    (GC012 constant-capture — the whole packed schedule duplicated into
    the executable, defeating the compile cache per plan).  Only the
    schedule SHAPES (n_rounds, phase count) specialize the compile.

    Returns a callable (state, health) -> (state', health',
    stats[N_CHAOS_STATS], safety[N_SAFETY]); state and health are
    donated, the schedule arrays are not (bench reps reuse them).  With
    SimConfig(blackbox=True) the signature gains a sim.BlackboxState —
    (state, health, blackbox) -> (state', health', blackbox', stats,
    safety) — and each round folds kernels.check_safety_groups instead,
    summing the per-group indicators into the identical safety counts
    while the black box records the offending (group, round) pairs; the
    blackbox=False graph is byte-identical to the pre-forensics build.
    Build once and call repeatedly — each make_runner call compiles
    afresh.  The underlying jit and its trailing schedule arguments are
    exposed as ``runner.jitted`` / ``runner.schedule_args`` for the
    graftcheck trace audit (tools/graftcheck/trace/inventory.py).

    Thin behavior-neutral wrapper since the runner-registry refactor:
    the construction lives in the unified factory
    (raft_tpu/multiraft/runner.py), instantiated from the schedules.py
    registry — byte-identical jaxpr (GC014 pins it).
    """
    from . import runner as runner_mod

    return runner_mod.make_runner(cfg, (compiled,))


def run_plan(
    cfg: sim_mod.SimConfig,
    state: sim_mod.SimState,
    compiled: CompiledChaos,
    health: Optional[sim_mod.HealthState] = None,
) -> Tuple[sim_mod.SimState, sim_mod.HealthState, jnp.ndarray, jnp.ndarray]:
    """Execute a whole compiled scenario in one jitted lax.scan.

    Returns (state', health', stats[N_CHAOS_STATS], safety[N_SAFETY]) —
    all device arrays; nothing crosses to the host inside the run.  The
    health planes are REQUIRED (the MTTR stats ride on HP_LEADERLESS):
    pass an existing HealthState to continue its windows, or None to start
    fresh.
    """
    if health is None:
        health = sim_mod.init_health(cfg)
    return make_runner(cfg, compiled)(state, health)
