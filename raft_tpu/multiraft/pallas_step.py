"""Fused Pallas kernel for the steady-state MultiRaft round.

In the steady state — every group has exactly one alive leader, all alive
peers share its term, and nobody's election timer can fire this round — a
protocol round touches only {election/heartbeat timers, log tail, matched,
commit}.  The XLA expression of that path (sim.step) makes several passes
over HBM; this kernel does ONE pass: each grid step streams a [P, BLOCK]
tile of every plane through VMEM, runs the whole round (tick + heartbeat +
appends + instant sync + sorting-network quorum commit) on the VPU, and
writes the six mutated planes back.

`steady_predicate` decides per batch whether the invariant holds; the
dispatcher `fast_step` lax.cond's between this kernel and the general
sim.step, so the fast path is a pure optimization with IDENTICAL semantics
(tests/test_pallas_step.py asserts bit-parity round by round).

Status: correct (bit-parity on TPU verified) but NOT the production path.
Measured on v5e-1 at 100k×5: this kernel ~240M ticks/s vs ~300M for the
fully-general XLA step and ~400M for the XLA steady-only expression — XLA's
own fusion of the [P, G] elementwise graph beats this hand-tiled version
(P=5 fills only 5/8 sublanes per tile, and the pallas pipeline adds per-
block overhead that the fused XLA loop avoids).  Kept as the scaffold for a
future multi-round-in-VMEM kernel (amortize HBM traffic over k rounds),
which is where a hand-written kernel can actually win.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import sim as sim_mod
from .kernels import INF, ROLE_LEADER
from .sim import SimConfig, SimState

BLOCK = 8192


def _steady_kernel(
    # inputs
    state_ref,
    term_ref,
    ee_ref,
    hb_ref,
    li_ref,
    lt_ref,
    matched_ref,
    commit_ref,
    voter_ref,
    crashed_ref,
    ts_ref,
    app_ref,
    # outputs
    ee_out,
    hb_out,
    li_out,
    lt_out,
    matched_out,
    commit_out,
    *,
    P: int,
    election_tick: int,
    heartbeat_tick: int,
):
    state = state_ref[...]
    term = term_ref[...]
    ee = ee_ref[...]
    hb = hb_ref[...]
    li = li_ref[...]
    lt = lt_ref[...]
    matched = matched_ref[...]
    commit = commit_ref[...]
    voter = voter_ref[...] != 0
    crashed = crashed_ref[...] != 0
    term_start = ts_ref[...]  # [1, BLOCK]
    app = app_ref[...]  # [1, BLOCK]

    alive = ~crashed
    # Timers tick by ROLE — a crashed (isolated) leader keeps ticking
    # (reference: raft.rs:1051-1079; isolation cuts the network, not the
    # clock).  Replication uses the ALIVE leader (exactly one by invariant).
    role_leader = state == ROLE_LEADER  # [P, B]
    is_leader = role_leader & alive
    has_leader = jnp.any(is_leader, axis=0, keepdims=True)  # [1, B]

    # --- tick (reference: raft.rs:1024-1079; no campaigns by invariant) ---
    ee2 = ee + 1
    leader_reset = role_leader & (ee2 >= election_tick)
    ee2 = jnp.where(leader_reset, 0, ee2)
    hb2 = jnp.where(role_leader, hb + 1, hb)
    want_beat = role_leader & (hb2 >= heartbeat_tick)
    hb2 = jnp.where(want_beat, 0, hb2)

    # --- appends at the (unique alive) leader ---
    n_app = jnp.where(has_leader, app, 0)  # [1, B]
    li2 = li + jnp.where(is_leader, n_app, 0)
    lt2 = jnp.where(is_leader, term, lt)
    lead_last = jnp.sum(jnp.where(is_leader, li2, 0), axis=0, keepdims=True)
    lead_lt = jnp.sum(jnp.where(is_leader, lt2, 0), axis=0, keepdims=True)

    lead_beat = jnp.any(want_beat & is_leader, axis=0, keepdims=True)
    sent = has_leader & (lead_beat | (n_app > 0))  # [1, B]

    # --- instant in-round sync of alive followers ---
    sync = sent & alive & ~is_leader
    ee2 = jnp.where(sync, 0, ee2)
    li2 = jnp.where(sync, lead_last, li2)
    lt2 = jnp.where(sync, lead_lt, lt2)
    matched2 = jnp.where(sync | (is_leader & sent), li2, matched)

    # --- quorum commit via odd-even transposition network over P rows
    # (reference: majority.rs:70-124).  Rows kept 2-D [1, B] for TPU tiling.
    rows = [
        jnp.where(voter[p : p + 1, :], matched2[p : p + 1, :], 0)
        for p in range(P)
    ]
    for pass_ in range(P):
        for i in range(pass_ % 2, P - 1, 2):
            hi = jnp.maximum(rows[i], rows[i + 1])
            lo = jnp.minimum(rows[i], rows[i + 1])
            rows[i], rows[i + 1] = hi, lo
    count = jnp.sum(voter.astype(jnp.int32), axis=0, keepdims=True)  # [1, B]
    qpos = count // 2
    mci = jnp.zeros_like(rows[0])
    for p in range(P):
        mci = jnp.where(qpos == p, rows[p], mci)

    ok = has_leader & sent & (mci >= term_start)
    lead_commit_old = jnp.sum(
        jnp.where(is_leader, commit, 0), axis=0, keepdims=True
    )
    lead_commit = jnp.where(ok, jnp.maximum(lead_commit_old, mci), lead_commit_old)
    commit2 = jnp.where((is_leader | sync) & sent, lead_commit, commit)

    ee_out[...] = ee2
    hb_out[...] = hb2
    li_out[...] = li2
    lt_out[...] = lt2
    matched_out[...] = matched2
    commit_out[...] = commit2


def steady_round(cfg: SimConfig):
    """Build the pallas_call for one fused steady round; returns
    fn(st, crashed, append_n) -> SimState."""
    P = cfg.n_peers
    G = cfg.n_groups
    block = min(BLOCK, G)
    grid = (pl.cdiv(G, block),)

    pg_spec = pl.BlockSpec((P, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    g_spec = pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM)

    kernel = functools.partial(
        _steady_kernel,
        P=P,
        election_tick=cfg.election_tick,
        heartbeat_tick=cfg.heartbeat_tick,
    )

    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pg_spec] * 10 + [g_spec] * 2,
        out_specs=[pg_spec] * 6,
        out_shape=[jax.ShapeDtypeStruct((P, G), jnp.int32)] * 6,
    )

    def fn(st: SimState, crashed: jnp.ndarray, append_n: jnp.ndarray) -> SimState:
        ee, hb, li, lt, matched, commit = call(
            st.state,
            st.term,
            st.election_elapsed,
            st.heartbeat_elapsed,
            st.last_index,
            st.last_term,
            st.matched,
            st.commit,
            st.voter_mask.astype(jnp.int32),
            crashed.astype(jnp.int32),
            st.term_start_index[None, :],
            append_n[None, :],
        )
        return st._replace(
            election_elapsed=ee,
            heartbeat_elapsed=hb,
            last_index=li,
            last_term=lt,
            matched=matched,
            commit=commit,
        )

    return fn


def steady_predicate(
    cfg: SimConfig, st: SimState, crashed: jnp.ndarray
) -> jnp.ndarray:
    """True iff EVERY group satisfies the steady invariant this round:
    no election timer can fire, exactly one alive leader, and every alive
    peer already shares the leader's term (so no role/vote/timeout-plane
    writes can occur)."""
    alive = ~crashed
    # 1. nobody campaigns this round
    will_fire = (
        (st.state != ROLE_LEADER)
        & (st.election_elapsed + 1 >= st.randomized_timeout)
        & st.voter_mask
    )
    no_campaign = ~jnp.any(will_fire)
    # 2. exactly one alive leader per group
    is_leader = (st.state == ROLE_LEADER) & alive
    one_leader = jnp.all(jnp.sum(is_leader.astype(jnp.int32), axis=0) == 1)
    # 3. alive peers at the leader's term
    lead_term = jnp.max(jnp.where(is_leader, st.term, 0), axis=0)
    terms_ok = jnp.all(jnp.where(alive, st.term == lead_term, True))
    return no_campaign & one_leader & terms_ok


def fast_step(cfg: SimConfig):
    """Dispatcher: the fused pallas round when steady, the general XLA step
    otherwise.  Same signature/semantics as sim.step."""
    pallas_fn = steady_round(cfg)

    def fn(st: SimState, crashed, append_n) -> SimState:
        pred = steady_predicate(cfg, st, crashed)
        return jax.lax.cond(
            pred,
            lambda args: pallas_fn(*args),
            lambda args: sim_mod.step(cfg, *args),
            (st, crashed, append_n),
        )

    return fn
