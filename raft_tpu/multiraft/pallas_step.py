"""Fused Pallas kernels for steady-state MultiRaft rounds.

In the steady state — every group has exactly one alive leader, all alive
peers share its term, and nobody's election timer can fire — a protocol
round touches only {election/heartbeat timers, log tail, matched, commit}.
The XLA expression of that path (sim.step) makes several passes over HBM;
these kernels stream each [P, BLOCK] tile through VMEM once and run **k
whole protocol rounds** on it before writing back, amortizing both HBM
traffic and per-block overhead over k rounds.

Relative shape measured on v5e-1 at 100k groups × 5 peers (steady append
load): at k = 1 the kernel loses to the general XLA step (fusion wins);
at k = 16..32 it is a multiple of the XLA step's throughput.  Absolute
ticks/s on the shared-tunnel TPU varied >2x between measurement windows
(410M-855M across bench rounds), so no single number is quoted here —
current figures come from `python bench.py`, which reports
min/median/max/spread_pct over >=5 repetitions and flags spreads >20%
(see docs/OBSERVABILITY.md).

`steady_predicate(cfg, st, crashed, horizon=k)` decides whether the
invariant provably holds for the next k rounds; `fast_multi_round` then
lax.cond's between the fused kernel and k sequential general steps, so the
fast path is a pure optimization with IDENTICAL semantics
(tests/test_pallas_step.py asserts bit-parity round by round; the crashed
mask and per-round append workload are held constant across the k rounds,
which is exactly the lockstep schedule ScalarCluster/bench drive).

Coverage matrix (docs/PERF.md): the INSTRUMENTED configurations ride the
fused path too — `with_health` tracks ticks_since_commit in-kernel and
folds the other planes closed-form; `with_counters` folds the CTR_* plane
closed-form (no campaigns/wins on a steady horizon, heartbeat fires and
commit deltas are arithmetic); `with_chaos` runs the loss-gated chaos
kernel (_steady_chaos_kernel): link plane healed by predicate, per-link
loss drawn IN-KERNEL with the (round, src, dst, group) counter PRNG,
bit-identical to k sequential sim.step(link=) rounds.  The chaos variants
stream packed sub-int32 operand planes (GC008 PACKED_PLANES registry).

Election damping (ISSUE 8): check_quorum/pre_vote configs — the deployed
raft-rs production configuration — ride their own fused kernel family
(_steady_damped_kernel, the same health/counters/chaos composition
surface), bit-identical to k `sim._damped_linked_step` rounds: on a
steady horizon damping has closed form — heartbeat acks saturate the
leader's recent_active row every heartbeat interval so the check-quorum
boundary provably passes (the kernel advances the boundary's
read-and-clear cycle in-kernel), leases are never tested and pre-vote is
dormant (no elections), and the low-term nudge cannot fire (uniform
terms).  steady_mask widens with the damping conditions
(kernels.cq_boundary_safe lossless; a conservative free-running bound on
the cq boundary under loss), so damped fusion needs the same
`election_tick > k` regime as chaos.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import kernels as kernels_mod
from . import planes
from . import sim as sim_mod
from .kernels import (
    CTR_COMMIT_ENTRIES,
    CTR_HEARTBEATS,
    HP_SINCE_COMMIT,
    HP_TERM_BUMPS,
    HP_VOTE_SPLITS,
    ROLE_FOLLOWER,
    ROLE_LEADER,
)
from .sim import HealthState, SimConfig, SimState

BLOCK = 8192


# --- packed kernel-operand planes (GC008 "packed planes" registry) ----------
#
# The fused kernels stream every operand plane HBM -> VMEM once per call, so
# each plane dropped from the operand list is G*4 bytes of memory traffic
# saved per fused block.  Three int32 [P, G] planes whose values are provably
# sub-int32 ride in ONE word each; the bounds are registered in
# tools/graftcheck/engine/overflow.py (PACKED_PLANES) and derived in
# docs/STATIC_ANALYSIS.md:
#
#   roles word  = state | leader_id << 2 | heartbeat_elapsed << 6
#                 (state < 4 by the ROLE_* code set; leader_id <= n_peers,
#                 asserted < 16; heartbeat_elapsed <= heartbeat_tick,
#                 asserted < 2**24)
#   masks word  = voter | member << 1 | crashed << 2   (three bools)


def _pack_roles(state, leader_id, hb):
    return state + (leader_id << 2) + (hb << 6)


def _unpack_roles(word):
    return word & 3, (word >> 2) & 15, word >> 6


def _pack_masks(voter, member, crashed):
    return (
        voter.astype(jnp.int32)
        + (member.astype(jnp.int32) << 1)
        + (crashed.astype(jnp.int32) << 2)
    )


def _unpack_masks(word):
    return (word & 1) != 0, ((word >> 1) & 1) != 0, ((word >> 2) & 1) != 0


def _steady_kernel(
    # inputs: state_ref, term_ref, ee_ref, hb_ref, li_ref, lt_ref,
    # matched_ref, commit_ref, voter_ref, member_ref, crashed_ref, ts_ref,
    # app_ref [+ tsc_ref when with_health]; then the outputs: ee, hb, li,
    # lt, matched, commit [+ tsc].  Flat *refs because the health variant
    # adds one input/output pair and pallas kernels take refs positionally.
    *refs,
    P: int,
    rounds: int,
    election_tick: int,
    heartbeat_tick: int,
    with_health: bool,
):
    n_in = 14 if with_health else 13
    (
        state_ref, term_ref, ee_ref, hb_ref, li_ref, lt_ref, matched_ref,
        commit_ref, voter_ref, member_ref, crashed_ref, ts_ref, app_ref,
    ) = refs[:13]
    ee_out, hb_out, li_out, lt_out, matched_out, commit_out = refs[
        n_in : n_in + 6
    ]
    state = state_ref[...]
    term = term_ref[...]
    ee = ee_ref[...]
    hb = hb_ref[...]
    li = li_ref[...]
    lt = lt_ref[...]
    matched = matched_ref[...]
    commit = commit_ref[...]
    voter = voter_ref[...] != 0
    member = member_ref[...] != 0
    crashed = crashed_ref[...] != 0
    term_start = ts_ref[...]  # [1, BLOCK]
    app = app_ref[...]  # [1, BLOCK]
    if with_health:
        tsc = refs[13][...]  # [1, BLOCK] ticks_since_commit plane
        maxc_prev = jnp.max(commit, axis=0, keepdims=True)  # [1, BLOCK]

    alive = ~crashed
    # Timers tick by ROLE — a crashed (isolated) leader keeps ticking
    # (reference: raft.rs:1051-1079; isolation cuts the network, not the
    # clock).  Replication uses the ALIVE leader (exactly one by invariant).
    role_leader = state == ROLE_LEADER  # [P, B]
    is_leader = role_leader & alive
    has_leader = jnp.any(is_leader, axis=0, keepdims=True)  # [1, B]
    # dtype= on every sum in the kernel: a bare jnp.sum widens to int64
    # under x64 — inside a Mosaic kernel that is not even lowerable, and in
    # interpret mode it silently changes the tile dtypes (GC007).
    count = jnp.sum(voter, axis=0, keepdims=True, dtype=jnp.int32)
    qpos = count // 2
    n_app = jnp.where(has_leader, app, 0)  # [1, B]

    for _ in range(rounds):
        # --- tick (reference: raft.rs:1024-1079; no campaigns by invariant)
        ee = ee + 1
        ee = jnp.where(role_leader & (ee >= election_tick), 0, ee)
        hb = jnp.where(role_leader, hb + 1, hb)
        want_beat = role_leader & (hb >= heartbeat_tick)
        hb = jnp.where(want_beat, 0, hb)

        # --- appends at the (unique alive) leader ---
        li = li + jnp.where(is_leader, n_app, 0)
        lt = jnp.where(is_leader, term, lt)
        lead_last = jnp.sum(
            jnp.where(is_leader, li, 0), axis=0, keepdims=True,
            dtype=jnp.int32,
        )
        lead_lt = jnp.sum(
            jnp.where(is_leader, lt, 0), axis=0, keepdims=True,
            dtype=jnp.int32,
        )

        lead_beat = jnp.any(want_beat & is_leader, axis=0, keepdims=True)
        sent = has_leader & (lead_beat | (n_app > 0))  # [1, B]

        # --- instant in-round sync of alive member followers (voters +
        # learners; non-members are outside the progress map) ---
        sync = sent & alive & member & ~is_leader
        ee = jnp.where(sync, 0, ee)
        li = jnp.where(sync, lead_last, li)
        lt = jnp.where(sync, lead_lt, lt)
        matched = jnp.where(sync | (is_leader & sent), li, matched)

        # --- quorum commit via odd-even transposition network over P rows
        # (reference: majority.rs:70-124).  Rows kept 2-D [1, B].
        rows = [
            jnp.where(voter[p : p + 1, :], matched[p : p + 1, :], 0)
            for p in range(P)
        ]
        for pass_ in range(P):
            for i in range(pass_ % 2, P - 1, 2):
                hi = jnp.maximum(rows[i], rows[i + 1])
                lo = jnp.minimum(rows[i], rows[i + 1])
                rows[i], rows[i + 1] = hi, lo
        mci = jnp.zeros_like(rows[0])
        for p in range(P):
            mci = jnp.where(qpos == p, rows[p], mci)

        ok = has_leader & sent & (mci >= term_start)
        lead_commit_old = jnp.sum(
            jnp.where(is_leader, commit, 0), axis=0, keepdims=True,
            dtype=jnp.int32,
        )
        lead_commit = jnp.where(
            ok, jnp.maximum(lead_commit_old, mci), lead_commit_old
        )
        commit = jnp.where((is_leader | sync) & sent, lead_commit, commit)

        if with_health:
            # The one health plane a steady round can move: per-round
            # commit-advance tracking for ticks_since_commit (the other
            # planes are closed-form over a steady horizon — see
            # steady_round's health wrapper).
            maxc = jnp.max(commit, axis=0, keepdims=True)
            tsc = jnp.where(maxc > maxc_prev, 0, tsc + 1)
            maxc_prev = maxc

    ee_out[...] = ee
    hb_out[...] = hb
    li_out[...] = li
    lt_out[...] = lt
    matched_out[...] = matched
    commit_out[...] = commit
    if with_health:
        refs[n_in + 6][...] = tsc


def _kernel_loss_draw(round_base, r, gids, lane, loss_rate):
    """In-kernel seeded per-link loss sample: kernels.link_loss_draw
    inlined with tile-global group ids (`gids` offset by the program id)
    and the precomputed (src, dst) `lane` plane — the ONE copy both the
    chaos and damped fused kernels draw from, so the (round, src, dst,
    group) PRNG keying cannot drift between them."""
    round_u = (round_base + jnp.int32(r)).astype(jnp.uint32)  # [1, B]
    x0 = kernels_mod._mix32(gids * jnp.uint32(0x9E3779B1) + round_u)
    x = kernels_mod._mix32(
        x0[None, :, :] ^ (lane * jnp.uint32(0x85EBCA6B))
    )  # [P, P, B]
    return (x % jnp.uint32(kernels_mod.LOSS_SCALE)).astype(
        jnp.int32
    ) < loss_rate


def _agree_event(agree, in_set, value, lead_f):
    """One wholesale-adoption agreement event (sim._merge_agree with the
    acting leader as the sender): pairs inside `in_set` agree to `value`;
    pairs with one side inside inherit the leader's row.  Shared by the
    chaos and damped fused kernels."""
    lead_row = jnp.sum(
        agree * lead_f[:, None, :], axis=0, dtype=jnp.int32
    )  # [P, B] = agree[leader, :]
    return jnp.where(
        in_set[:, None, :] & in_set[None, :, :],
        value[None, :, :],
        jnp.where(
            in_set[:, None, :],
            lead_row[None, :, :],
            jnp.where(in_set[None, :, :], lead_row[:, None, :], agree),
        ),
    )


def _quorum_tile(matched, voter, qpos, P):
    """Majority index of a [P, B] matched tile over its voter rows: the
    same odd-even transposition network as the plain steady kernel (the
    in-kernel twin of sim._quorum_index for the non-joint case)."""
    rows = [
        jnp.where(voter[p : p + 1, :], matched[p : p + 1, :], 0)
        for p in range(P)
    ]
    for pass_ in range(P):
        for i in range(pass_ % 2, P - 1, 2):
            hi = jnp.maximum(rows[i], rows[i + 1])
            lo = jnp.minimum(rows[i], rows[i + 1])
            rows[i], rows[i + 1] = hi, lo
    mci = jnp.zeros_like(rows[0])
    for p in range(P):
        mci = jnp.where(qpos == p, rows[p], mci)
    return mci


def _steady_chaos_kernel(
    # inputs: roles_ref (packed state|leader_id|hb), ee, li, lt, commit,
    # matched_row, masks_ref (packed voter|member|crashed) [P, B]; agree,
    # loss_rate [P, P, B]; ts, lead_term, app, round_base [1, B]
    # [+ tsc when with_health]; outputs: roles, ee, li, lt, commit,
    # matched_row, agree [+ tsc].
    *refs,
    P: int,
    block: int,
    rounds: int,
    election_tick: int,
    heartbeat_tick: int,
    with_health: bool,
):
    n_in = 14 if with_health else 13
    (
        roles_ref, ee_ref, li_ref, lt_ref, commit_ref, matched_ref,
        masks_ref, agree_ref, loss_ref, ts_ref, ltm_ref, app_ref, rb_ref,
    ) = refs[:13]
    (
        roles_out, ee_out, li_out, lt_out, commit_out, matched_out,
        agree_out,
    ) = refs[n_in : n_in + 7]
    state, leader_id, hb = _unpack_roles(roles_ref[...])
    voter, member, crashed = _unpack_masks(masks_ref[...])
    ee = ee_ref[...]
    li = li_ref[...]
    lt = lt_ref[...]
    commit = commit_ref[...]
    matched_row = matched_ref[...]  # the acting leader's tracker row
    agree = agree_ref[...]  # [P, P, B] pairwise log agreement
    loss_rate = loss_ref[...]  # [P, P, B] fixed-point per-link loss
    ts = ts_ref[...]  # [1, B] acting leader's term_start_index
    ltm = ltm_ref[...]  # [1, B] acting leader's term
    app = app_ref[...]  # [1, B]
    round_base = rb_ref[...]  # [1, B] absolute round index of round 0
    if with_health:
        tsc = refs[13][...]
        maxc_prev = jnp.max(commit, axis=0, keepdims=True)

    alive = ~crashed
    role_leader = state == ROLE_LEADER
    is_lead = role_leader & alive  # exactly one per group by the predicate
    has_leader = jnp.any(is_lead, axis=0, keepdims=True)  # [1, B]
    lead_f = is_lead.astype(jnp.int32)
    p_iota = jax.lax.broadcasted_iota(jnp.int32, (P, 1), 0)
    # dtype= on every sum: see _steady_kernel (GC007).
    lead_id_val = jnp.sum(
        lead_f * (p_iota + 1), axis=0, keepdims=True, dtype=jnp.int32
    )
    count = jnp.sum(voter, axis=0, keepdims=True, dtype=jnp.int32)
    qpos = count // 2
    n_app = jnp.where(has_leader, app, 0)  # [1, B]
    # Global group ids for the (round, src, dst, group)-keyed loss PRNG —
    # the draw must be bit-identical to kernels.link_loss_draw on the full
    # batch, so the iota is offset by this tile's first column.
    gids = (
        jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
        + pl.program_id(0) * block
    ).astype(jnp.uint32)
    s_io = jax.lax.broadcasted_iota(jnp.uint32, (P, P, 1), 0)
    d_io = jax.lax.broadcasted_iota(jnp.uint32, (P, P, 1), 1)
    lane = s_io * jnp.uint32(P) + d_io + jnp.uint32(1)

    def lead_gather(plane):  # [P, B] -> [1, B]: the acting leader's value
        return jnp.sum(plane * lead_f, axis=0, keepdims=True, dtype=jnp.int32)

    def agree_event(agree, in_set, value):
        # sim._linked_step's triple-where, shared with the damped kernel.
        return _agree_event(agree, in_set, value, lead_f)

    for r in range(rounds):
        # --- seeded per-link loss draw (the shared in-kernel PRNG).
        drop = _kernel_loss_draw(round_base, r, gids, lane, loss_rate)
        # Forward (leader -> v) and reverse (v -> leader) delivery for this
        # round; the link plane itself is all-up among alive peers by the
        # steady predicate, so only the loss sample gates delivery.
        dfl = jnp.any(drop & is_lead[:, None, :], axis=0)  # [P, B]
        dtl = jnp.any(drop & is_lead[None, :, :], axis=1)
        fwd = ~dfl & alive & ~is_lead
        rev = ~dtl & alive & ~is_lead

        # --- tick (identical to the plain steady kernel)
        ee = ee + 1
        ee = jnp.where(role_leader & (ee >= election_tick), 0, ee)
        hb = jnp.where(role_leader, hb + 1, hb)
        want_beat = role_leader & (hb >= heartbeat_tick)
        hb = jnp.where(want_beat, 0, hb)
        beat = jnp.any(want_beat & is_lead, axis=0, keepdims=True)  # [1, B]

        # Round-start snapshots of the acting leader's cursors (the
        # wave payloads are queued before any delivery mutates them).
        c_l = lead_gather(commit)  # [1, B]
        li_l = lead_gather(li)
        lt_l = lead_gather(lt)

        # --- wave 1: heartbeat delivery (terms are all equal, so every
        # delivered heartbeat is accepted) + the reverse-link response.
        h_acc = fwd & beat & member
        state = jnp.where(h_acc, ROLE_FOLLOWER, state)
        leader_id = jnp.where(h_acc, lead_id_val, leader_id)
        ee = jnp.where(h_acc, 0, ee)
        hb_val = jnp.minimum(matched_row, c_l)
        commit = jnp.where(h_acc, jnp.maximum(commit, hb_val), commit)
        resumed = h_acc & rev  # pr.resume() at the leader

        # --- wave 3 pass 1: heartbeat-triggered catch-up appends for
        # lagging members (cu implies both links up, so the send adopts
        # and the ack lands in the leader's matched row).
        cu = resumed & (matched_row < li_l)
        commit = jnp.where(cu, jnp.maximum(commit, c_l), commit)
        matched_row = jnp.where(
            cu, jnp.maximum(matched_row, li_l), matched_row
        )
        li = jnp.where(cu, li_l, li)
        lt = jnp.where(cu, lt_l, lt)
        sent1 = jnp.any(cu, axis=0, keepdims=True)
        agree = agree_event(agree, cu | (is_lead & sent1), li_l)

        # --- stage-A quorum commit at the leader off the fresh acks.
        mci = _quorum_tile(matched_row, voter, qpos, P)
        ok_a = has_leader & (count > 0) & (mci >= ts)
        c_new = jnp.where(ok_a, jnp.maximum(c_l, mci), c_l)
        adv = c_new > c_l
        commit = jnp.where(is_lead, c_new, commit)

        # --- pass 2: a commit advance re-broadcasts to sendable members
        # (Replicate probes and freshly resumed ones).
        agree_l = jnp.sum(
            agree * lead_f[:, None, :], axis=0, dtype=jnp.int32
        )
        sendable = (matched_row > 0) | resumed
        msg2 = fwd & member & adv & sendable
        adopt2 = msg2 & ((agree_l >= li_l) | rev)
        state = jnp.where(msg2, ROLE_FOLLOWER, state)
        leader_id = jnp.where(msg2, lead_id_val, leader_id)
        ee = jnp.where(msg2, 0, ee)
        li = jnp.where(adopt2, li_l, li)
        lt = jnp.where(adopt2, lt_l, lt)
        matched_row = jnp.where(
            adopt2 & rev, jnp.maximum(matched_row, li_l), matched_row
        )
        agree = agree_event(agree, adopt2 | (is_lead & jnp.any(
            adopt2, axis=0, keepdims=True)), li_l)

        # --- stage-B commit + the post-advance commit propagation.
        mci2 = _quorum_tile(matched_row, voter, qpos, P)
        ok_b = has_leader & (count > 0) & (mci2 >= ts)
        c_new2 = jnp.where(ok_b, jnp.maximum(c_new, mci2), c_new)
        commit = jnp.where(is_lead, c_new2, commit)
        agree_l2 = jnp.sum(
            agree * lead_f[:, None, :], axis=0, dtype=jnp.int32
        )
        sendable2 = (matched_row > 0) | resumed
        elig = (
            fwd
            & member
            & sendable2
            & ((agree_l2 >= li_l) | rev)
            & (c_new2 > c_l)
        )
        commit = jnp.where(elig, jnp.maximum(commit, c_new2), commit)

        # --- the round's append workload at the leader.
        sent_b = has_leader & (n_app > 0)
        li = li + jnp.where(is_lead, n_app, 0)
        lt = jnp.where(is_lead & sent_b, ltm, lt)
        lead_last = li_l + n_app  # [1, B]
        pr_ok = (matched_row > 0) | resumed
        sync_msg = sent_b & fwd & member & ~is_lead & pr_ok
        agree_l3 = jnp.sum(
            agree * lead_f[:, None, :], axis=0, dtype=jnp.int32
        )
        sync_b = sync_msg & ((agree_l3 >= li_l) | rev)
        state = jnp.where(sync_msg, ROLE_FOLLOWER, state)
        leader_id = jnp.where(sync_msg, lead_id_val, leader_id)
        ee = jnp.where(sync_msg, 0, ee)
        li = jnp.where(sync_b, lead_last, li)
        lt = jnp.where(sync_b, ltm, lt)
        acked = (sync_b & rev) | (is_lead & sent_b)
        matched_row = jnp.where(
            acked, jnp.maximum(matched_row, lead_last), matched_row
        )
        agree = agree_event(agree, sync_b | (is_lead & sent_b), lead_last)
        mci3 = _quorum_tile(matched_row, voter, qpos, P)
        ok_c = sent_b & (count > 0) & (mci3 >= ts)
        lead_commit = jnp.where(ok_c, jnp.maximum(c_new2, mci3), c_new2)
        commit = jnp.where(is_lead, lead_commit, commit)
        commit = jnp.where(
            sync_b, jnp.maximum(commit, lead_commit), commit
        )

        if with_health:
            maxc = jnp.max(commit, axis=0, keepdims=True)
            tsc = jnp.where(maxc > maxc_prev, 0, tsc + 1)
            maxc_prev = maxc

    roles_out[...] = _pack_roles(state, leader_id, hb)
    ee_out[...] = ee
    li_out[...] = li
    lt_out[...] = lt
    commit_out[...] = commit
    matched_out[...] = matched_row
    agree_out[...] = agree
    if with_health:
        refs[n_in + 7][...] = tsc


def _fold_counters(cfg: SimConfig, k: int, st_in, st_out, counters):
    """Closed-form CTR_* fold for a steady k-round horizon: campaigns and
    elections won are 0 (the predicate forbids both), heartbeat fires per
    role-leader are (hb0 + k) // heartbeat_tick (the timer resets on every
    fire), and commit deltas telescope because commit is monotone —
    bit-identical to threading counters through k sim.steps
    (tests/test_pallas_step.py)."""
    role_leader = st_in.state == ROLE_LEADER
    fires = jnp.where(
        role_leader,
        (st_in.heartbeat_elapsed + jnp.int32(k))
        // jnp.int32(cfg.heartbeat_tick),
        0,
    )
    # dtype= on the sums: a bare jnp.sum widens to int64 under x64 (GC007).
    hb_total = jnp.sum(fires, dtype=jnp.int32)
    commit_total = jnp.sum(st_out.commit - st_in.commit, dtype=jnp.int32)
    return (
        counters.at[CTR_HEARTBEATS]
        .add(hb_total)
        .at[CTR_COMMIT_ENTRIES]
        .add(commit_total)
    )


def _steady_health_fold(cfg: SimConfig, rounds: int, health, tsc_out):
    """Closed-form health fold for a steady horizon: the churn window
    resets iff a round with window_pos == 0 falls inside [pos, pos +
    rounds), and every in-horizon bump is 0."""
    pos = health.window_pos
    window = jnp.int32(cfg.health_window)
    crossed = (pos == 0) | (pos + jnp.int32(rounds) > window)
    planes = jnp.stack(
        [
            jnp.zeros_like(tsc_out),  # leaderless: a leader held all k
            tsc_out,
            jnp.where(crossed, 0, health.planes[HP_TERM_BUMPS]),
            health.planes[HP_VOTE_SPLITS],
        ]
    )
    new_pos = (pos + jnp.int32(rounds)) % window
    return HealthState(planes, new_pos)


def steady_round(
    cfg: SimConfig,
    rounds: int = 1,
    with_health: bool = False,
    interpret: bool = False,
    with_chaos: bool = False,
    with_counters: bool = False,
):
    """Build the pallas_call for `rounds` fused steady protocol rounds;
    returns fn(st, crashed, append_n) -> SimState (same crashed/append each
    round).

    With `with_health`, the returned fn takes a HealthState extra and
    returns it updated, bit-identical to threading sim.step's health extra
    through the same rounds.  Only ticks_since_commit needs per-round
    tracking (one extra [1, BLOCK] VMEM plane); the other planes are
    closed-form over a steady horizon — no campaigns can fire and the
    alive leader holds, so leaderless_ticks lands at 0, vote_splits is
    unchanged, term bumps are 0 and the churn window only needs its
    position advanced (with one reset if a window boundary falls inside
    the horizon).

    With `with_counters`, the fn takes/returns the [N_COUNTERS] int32
    plane; the per-round event counts are closed-form over a steady
    horizon (_fold_counters).

    With `with_chaos`, the fn signature grows (loss_rate int32[P, P, G],
    round_base int32[]) after append_n and the round runs the loss-gated
    chaos kernel (_steady_chaos_kernel): per-link loss draws are sampled
    in-kernel with the (round, src, dst, group) counter PRNG, bit-identical
    to `rounds` sequential sim.step(link=healed & ~loss_draw) calls.  The
    extras order is always (loss, round_base), counters, health —
    sim.step's extras convention.

    Damping-on configs (SimConfig.check_quorum / pre_vote) build the
    damped kernel family instead (_steady_damped_kernel) with the same
    signatures per flag combination, bit-identical to `rounds` sequential
    damped wave rounds (sim._damped_linked_step) — including the
    check-quorum boundary's recent_active read-and-clear cycle."""
    P = cfg.n_peers
    G = cfg.n_groups
    block = min(BLOCK, G)
    grid = (pl.cdiv(G, block),)

    pg_spec = pl.BlockSpec((P, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    g_spec = pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM)

    if cfg.check_quorum or cfg.pre_vote:
        # Election-damping configs route to the damped kernel family
        # (ISSUE 8): same composition surface (health/counters/chaos),
        # built separately so the undamped graphs stay byte-identical.
        return _build_damped_round(
            cfg, rounds, with_health, with_counters, with_chaos, interpret,
            pg_spec, g_spec, grid, block,
        )

    if with_chaos:
        return _build_chaos_round(
            cfg, rounds, with_health, with_counters, interpret,
            pg_spec, g_spec, grid, block,
        )

    kernel = functools.partial(
        _steady_kernel,
        P=P,
        rounds=rounds,
        election_tick=cfg.election_tick,
        heartbeat_tick=cfg.heartbeat_tick,
        with_health=with_health,
    )

    n_g_in = 3 if with_health else 2
    n_out = 7 if with_health else 6
    out_shape = [jax.ShapeDtypeStruct((P, G), jnp.int32)] * 6
    out_specs = [pg_spec] * 6
    if with_health:
        out_shape = out_shape + [jax.ShapeDtypeStruct((1, G), jnp.int32)]
        out_specs = out_specs + [g_spec]
    del n_out

    # `interpret` is for CPU runs with no Mosaic lowering (bench artifact
    # jobs).  Only passed when set: the test fixtures patch pl.pallas_call
    # with setdefault("interpret", True), which an explicit False would
    # defeat.
    interp_kw = {"interpret": True} if interpret else {}
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pg_spec] * 11 + [g_spec] * n_g_in,
        out_specs=out_specs,
        out_shape=out_shape,
        **interp_kw,
    )

    def _run(
        st: SimState,
        crashed: jnp.ndarray,
        append_n: jnp.ndarray,
        tsc_in: Optional[jnp.ndarray],
    ):
        # The acting leader is fixed for the whole steady horizon (no
        # elections, constant crash mask), so its tracker row is gathered
        # once outside the kernel and scattered back after.
        is_leader = (st.state == ROLE_LEADER) & ~crashed
        f = is_leader.astype(jnp.int32)
        # dtype= keeps the gathered tracker rows int32 under x64: these
        # feed pallas_call inputs whose BlockSpecs assume int32 (GC007).
        acting_row = jnp.sum(
            st.matched * f[:, None, :], axis=0, dtype=jnp.int32
        )  # [P, G]
        ts_acting = jnp.sum(
            st.term_start_index * f, axis=0, dtype=jnp.int32
        )  # [G]

        inputs = (
            st.state,
            st.term,
            st.election_elapsed,
            st.heartbeat_elapsed,
            st.last_index,
            st.last_term,
            acting_row,
            st.commit,
            st.voter_mask.astype(jnp.int32),
            (st.voter_mask | st.learner_mask).astype(jnp.int32),
            crashed.astype(jnp.int32),
            ts_acting[None, :],
            append_n[None, :],
        )
        if tsc_in is not None:
            inputs = inputs + (tsc_in[None, :],)
        outs = call(*inputs)
        ee, hb, li, lt, new_row, commit = outs[:6]
        tsc_out = outs[6][0] if tsc_in is not None else None
        matched = jnp.where(
            is_leader[:, None, :], new_row[None, :, :], st.matched
        )
        # Pairwise log-agreement update, applied once for the whole horizon
        # (idempotent per round: the sync set is constant while steady, and
        # only the final leader last_index matters).
        member = st.voter_mask | st.learner_mask
        in_s = (member & ~crashed) | is_leader
        lead_last = jnp.max(jnp.where(is_leader, li, 0), axis=0)  # [G]
        lead_row = jnp.sum(
            st.agree * f[:, None, :], axis=0, dtype=jnp.int32
        )  # [P, G]
        agree = jnp.where(
            in_s[:, None, :] & in_s[None, :, :],
            lead_last[None, None, :],
            jnp.where(
                in_s[:, None, :],
                lead_row[None, :, :],
                jnp.where(in_s[None, :, :], lead_row[:, None, :], st.agree),
            ),
        )
        out = st._replace(
            election_elapsed=ee,
            heartbeat_elapsed=hb,
            last_index=li,
            last_term=lt,
            matched=matched,
            commit=commit,
            agree=agree,
        )
        return out, tsc_out

    def fn(
        st: SimState, crashed: jnp.ndarray, append_n: jnp.ndarray
    ) -> SimState:
        return _run(st, crashed, append_n, None)[0]

    def fn_health(
        st: SimState,
        crashed: jnp.ndarray,
        append_n: jnp.ndarray,
        health: HealthState,
    ):
        out, tsc_out = _run(
            st, crashed, append_n, health.planes[HP_SINCE_COMMIT]
        )
        # Closed-form health fold for a steady horizon (see the docstring).
        return out, _steady_health_fold(cfg, rounds, health, tsc_out)

    if not with_counters:
        return fn_health if with_health else fn

    # Counters ride the fused path as a closed-form fold around either
    # variant above (extras order: counters before health, like sim.step).
    if with_health:

        def fn_counted_health(st, crashed, append_n, counters, health):
            out, health2 = fn_health(st, crashed, append_n, health)
            return out, _fold_counters(cfg, rounds, st, out, counters), health2

        return fn_counted_health

    def fn_counted(st, crashed, append_n, counters):
        out = fn(st, crashed, append_n)
        return out, _fold_counters(cfg, rounds, st, out, counters)

    return fn_counted


def _build_chaos_round(
    cfg: SimConfig,
    rounds: int,
    with_health: bool,
    with_counters: bool,
    interpret: bool,
    pg_spec,
    g_spec,
    grid,
    block: int,
):
    """The chaos-on (loss-gated) fused steady round: see steady_round's
    docstring.  Separate builder so the chaos machinery cannot perturb the
    plain kernel's traced graph (pinned by jaxpr equality in
    tests/test_pallas_step.py)."""
    P = cfg.n_peers
    G = cfg.n_groups
    # The packed roles word budgets 4 bits for leader_id and the rest for
    # heartbeat_elapsed (bound: <= heartbeat_tick) — see the PACKED_PLANES
    # registry (tools/graftcheck/engine/overflow.py).
    assert P <= 15, "packed roles word budgets 4 bits for leader_id"
    assert cfg.heartbeat_tick < (1 << 24), (
        "packed roles word budgets 24 bits for heartbeat_elapsed"
    )
    ppg_spec = pl.BlockSpec(
        (P, P, block), lambda i: (0, 0, i), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _steady_chaos_kernel,
        P=P,
        block=block,
        rounds=rounds,
        election_tick=cfg.election_tick,
        heartbeat_tick=cfg.heartbeat_tick,
        with_health=with_health,
    )
    n_g_in = 5 if with_health else 4
    out_shape = [jax.ShapeDtypeStruct((P, G), jnp.int32)] * 6 + [
        jax.ShapeDtypeStruct((P, P, G), jnp.int32)
    ]
    out_specs = [pg_spec] * 6 + [ppg_spec]
    if with_health:
        out_shape = out_shape + [jax.ShapeDtypeStruct((1, G), jnp.int32)]
        out_specs = out_specs + [g_spec]
    interp_kw = {"interpret": True} if interpret else {}
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pg_spec] * 7 + [ppg_spec] * 2 + [g_spec] * n_g_in,
        out_specs=out_specs,
        out_shape=out_shape,
        **interp_kw,
    )

    def _run(
        st: SimState,
        crashed: jnp.ndarray,
        append_n: jnp.ndarray,
        loss_rate: jnp.ndarray,
        round_base: jnp.ndarray,
        tsc_in: Optional[jnp.ndarray],
    ):
        is_leader = (st.state == ROLE_LEADER) & ~crashed
        f = is_leader.astype(jnp.int32)
        # dtype= keeps the gathered rows int32 under x64 (GC007).
        acting_row = jnp.sum(
            st.matched * f[:, None, :], axis=0, dtype=jnp.int32
        )  # [P, G]
        ts_acting = jnp.sum(
            st.term_start_index * f, axis=0, dtype=jnp.int32
        )  # [G]
        lead_term = jnp.sum(st.term * f, axis=0, dtype=jnp.int32)  # [G]
        member = st.voter_mask | st.learner_mask
        rb = jnp.broadcast_to(
            jnp.reshape(round_base.astype(jnp.int32), (1, 1)), (1, G)
        )
        inputs = (
            _pack_roles(st.state, st.leader_id, st.heartbeat_elapsed),
            st.election_elapsed,
            st.last_index,
            st.last_term,
            st.commit,
            acting_row,
            _pack_masks(st.voter_mask, member, crashed),
            st.agree,
            loss_rate,
            ts_acting[None, :],
            lead_term[None, :],
            append_n[None, :],
            rb,
        )
        if tsc_in is not None:
            inputs = inputs + (tsc_in[None, :],)
        outs = call(*inputs)
        roles, ee, li, lt, commit, new_row, agree = outs[:7]
        tsc_out = outs[7][0] if tsc_in is not None else None
        state, leader_id, hb = _unpack_roles(roles)
        matched = jnp.where(
            is_leader[:, None, :], new_row[None, :, :], st.matched
        )
        out = st._replace(
            state=state,
            leader_id=leader_id,
            election_elapsed=ee,
            heartbeat_elapsed=hb,
            last_index=li,
            last_term=lt,
            matched=matched,
            commit=commit,
            agree=agree,
        )
        return out, tsc_out

    # Static extras layout, resolved at build time (counters before health,
    # sim.step's extras order); None = absent.
    idx_counters = 0 if with_counters else None
    idx_health = (1 if with_counters else 0) if with_health else None

    def fn(st, crashed, append_n, loss_rate, round_base, *extras):
        counters = None if idx_counters is None else extras[idx_counters]
        health = None if idx_health is None else extras[idx_health]
        tsc_in = None if health is None else health.planes[HP_SINCE_COMMIT]
        out, tsc_out = _run(
            st, crashed, append_n, loss_rate, round_base, tsc_in
        )
        res: tuple = (out,)
        if counters is not None:
            res = res + (_fold_counters(cfg, rounds, st, out, counters),)
        if health is not None:
            res = res + (_steady_health_fold(cfg, rounds, health, tsc_out),)
        if idx_counters is None and idx_health is None:
            return out
        return res

    return fn


def _steady_damped_kernel(
    # inputs: roles_ref (packed state|leader_id|hb), ee, li, lt, commit,
    # matched_row (acting leader's tracker row), ra (acting leader's
    # recent_active row, 0/1), masks_ref (packed voter|member|crashed)
    # [P, B]; agree [P, P, B] [+ loss_rate [P, P, B] when with_loss];
    # ts, lead_term, app [1, B] [+ round_base when with_loss, tsc when
    # with_health]; outputs: roles, ee, li, lt, commit, matched_row, ra,
    # agree [+ tsc].
    *refs,
    P: int,
    block: int,
    rounds: int,
    election_tick: int,
    heartbeat_tick: int,
    with_health: bool,
    with_cq: bool,
    with_loss: bool,
):
    """The damping-on steady round: k rounds of sim._damped_linked_step's
    wave replay specialized to the steady invariant (uniform terms among
    alive peers, one alive acting leader, all links up among alive peers,
    no campaign can fire), bit-identically — including the check-quorum
    read-and-clear `recent_active` cycle at the leader's election-timeout
    boundary (`with_cq`; the steady predicate proves every in-horizon
    boundary passes, so the boundary's only effect is the clear), the
    damped probe rule (first-probe prev from modeled cursors, retry-chain
    adoption whose acks land one stage later than probe-matched ones), and
    — `with_loss` — the chaos engine's in-kernel per-link loss draws.
    Leases and the low-term nudge are provably dormant on a steady horizon
    (no vote requests, uniform terms), so they need no carry."""
    n_in = 12 + (2 if with_loss else 0) + (1 if with_health else 0)
    i = 0
    (
        roles_ref, ee_ref, li_ref, lt_ref, commit_ref, matched_ref,
        ra_ref, masks_ref, agree_ref,
    ) = refs[:9]
    i = 9
    if with_loss:
        loss_ref = refs[i]
        i += 1
    ts_ref, ltm_ref, app_ref = refs[i : i + 3]
    i += 3
    if with_loss:
        rb_ref = refs[i]
        i += 1
    if with_health:
        tsc_ref = refs[i]
    (
        roles_out, ee_out, li_out, lt_out, commit_out, matched_out,
        ra_out, agree_out,
    ) = refs[n_in : n_in + 8]
    state, leader_id, hb = _unpack_roles(roles_ref[...])
    voter, member, crashed = _unpack_masks(masks_ref[...])
    ee = ee_ref[...]
    li = li_ref[...]
    lt = lt_ref[...]
    commit = commit_ref[...]
    matched_row = matched_ref[...]
    ra = ra_ref[...] != 0  # [P, B] the acting leader's recent_active row
    agree = agree_ref[...]
    ts = ts_ref[...]  # [1, B] acting leader's term_start_index
    ltm = ltm_ref[...]  # [1, B] acting leader's term
    app = app_ref[...]  # [1, B]
    if with_loss:
        loss_rate = loss_ref[...]  # [P, P, B]
        round_base = rb_ref[...]  # [1, B]
    if with_health:
        tsc = tsc_ref[...]
        maxc_prev = jnp.max(commit, axis=0, keepdims=True)

    alive = ~crashed
    role_leader = state == ROLE_LEADER
    is_lead = role_leader & alive  # exactly one per group by the predicate
    has_leader = jnp.any(is_lead, axis=0, keepdims=True)  # [1, B]
    lead_f = is_lead.astype(jnp.int32)
    p_iota = jax.lax.broadcasted_iota(jnp.int32, (P, 1), 0)
    # dtype= on every sum: see _steady_kernel (GC007).
    lead_id_val = jnp.sum(
        lead_f * (p_iota + 1), axis=0, keepdims=True, dtype=jnp.int32
    )
    count = jnp.sum(voter, axis=0, keepdims=True, dtype=jnp.int32)
    qpos = count // 2
    n_app = jnp.where(has_leader, app, 0)  # [1, B]
    sent_b = has_leader & (n_app > 0)
    if with_loss:
        gids = (
            jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
            + pl.program_id(0) * block
        ).astype(jnp.uint32)
        s_io = jax.lax.broadcasted_iota(jnp.uint32, (P, P, 1), 0)
        d_io = jax.lax.broadcasted_iota(jnp.uint32, (P, P, 1), 1)
        lane = s_io * jnp.uint32(P) + d_io + jnp.uint32(1)

    def lead_gather(plane):  # [P, B] -> [1, B]: the acting leader's value
        return jnp.sum(plane * lead_f, axis=0, keepdims=True, dtype=jnp.int32)

    def agree_event(agree, in_set, value):
        # sim._merge_agree with the acting leader as the sender — the
        # same shared triple-where as the chaos kernel.
        return _agree_event(agree, in_set, value, lead_f)

    def agree_lead(agree):  # [P, B]: agree[leader, :] right now
        return jnp.sum(agree * lead_f[:, None, :], axis=0, dtype=jnp.int32)

    for r in range(rounds):
        if with_loss:
            # Seeded per-link loss — the round's single delivery draw,
            # from the same shared in-kernel PRNG as the chaos kernel.
            drop = _kernel_loss_draw(round_base, r, gids, lane, loss_rate)
            dfl = jnp.any(drop & is_lead[:, None, :], axis=0)  # [P, B]
            dtl = jnp.any(drop & is_lead[None, :, :], axis=1)
            fwd = ~dfl & alive & ~is_lead
            rev = ~dtl & alive & ~is_lead
        else:
            fwd = alive & ~is_lead
            rev = fwd

        # --- tick, incl. the leader's election-timeout boundary.  With
        # check-quorum the boundary READS-AND-CLEARS the leader's
        # recent_active row; the predicate proves the read passes (and
        # that no crashed stale leader reaches its boundary), so the
        # deposition/heartbeat-suppression arms are provably dead.
        ee = ee + 1
        boundary = role_leader & (ee >= election_tick)
        ee = jnp.where(boundary, 0, ee)
        if with_cq:
            lead_bnd = jnp.any(
                boundary & is_lead, axis=0, keepdims=True
            )  # [1, B]
            ra = jnp.where(lead_bnd, is_lead, ra)  # clear to the self row
        hb = jnp.where(role_leader, hb + 1, hb)
        want_beat = role_leader & (hb >= heartbeat_tick)
        hb = jnp.where(want_beat, 0, hb)
        beat = jnp.any(want_beat & is_lead, axis=0, keepdims=True)  # [1, B]

        # Round-start snapshots of the acting leader's cursors.
        c_l = lead_gather(commit)  # [1, B]
        li_l = lead_gather(li)
        lt_l = lead_gather(lt)

        # --- wave 1: heartbeat delivery (terms uniform: every delivered
        # heartbeat is accepted, no nudges can fire).
        h_acc = fwd & beat & member
        state = jnp.where(h_acc, ROLE_FOLLOWER, state)
        leader_id = jnp.where(h_acc, lead_id_val, leader_id)
        ee = jnp.where(h_acc, 0, ee)
        hb_val = jnp.minimum(matched_row, c_l)
        commit = jnp.where(h_acc, jnp.maximum(commit, hb_val), commit)

        # --- wave 2a: heartbeat responses resume probes and set the
        # leader's recent_active bits; lagging members trigger catch-up.
        resumed = h_acc & rev
        ra = ra | resumed
        cu = resumed & (matched_row < li_l)

        # --- wave 3: catch-up appends with the DAMPED probe rule: prev
        # comes from the modeled cursor (never-acked members probe from
        # the election noop), non-matching probes start a retry chain
        # whose wholesale adoption lands after stage A and whose ack
        # folds only at the wave-6 stage (sim._damped_linked_step).
        agree_l = agree_lead(agree)
        prev3 = jnp.where(matched_row == 0, ts - 1, li_l)
        probe3 = agree_l >= prev3
        adopt3 = cu & probe3
        retry3 = cu & ~probe3  # cu implies the reverse link is up
        commit = jnp.where(adopt3, jnp.maximum(commit, c_l), commit)
        li = jnp.where(adopt3, li_l, li)
        lt = jnp.where(adopt3, lt_l, lt)
        agree = agree_event(
            agree,
            adopt3 | (is_lead & jnp.any(adopt3, axis=0, keepdims=True)),
            li_l,
        )
        ack3 = adopt3

        # --- wave 4: stage fold over the probe-matched acks + stage-A
        # quorum commit at the leader.
        matched_row = jnp.where(
            ack3, jnp.maximum(matched_row, li_l), matched_row
        )
        ra = ra | ack3
        mci = _quorum_tile(matched_row, voter, qpos, P)
        ok_a = has_leader & (count > 0) & (mci >= ts)
        c_new = jnp.where(ok_a, jnp.maximum(c_l, mci), c_l)
        adv = c_new > c_l
        commit = jnp.where(is_lead, c_new, commit)

        # --- wave-3 retry resends (the surviving maybe_decr chain): the
        # resend lands as wholesale adoption AFTER stage A; its ack joins
        # the wave-6 fold below.
        commit = jnp.where(retry3, jnp.maximum(commit, c_l), commit)
        li = jnp.where(retry3, li_l, li)
        lt = jnp.where(retry3, lt_l, lt)
        agree = agree_event(
            agree,
            retry3 | (is_lead & jnp.any(retry3, axis=0, keepdims=True)),
            li_l,
        )

        # --- wave 5: the commit-advance re-broadcast to sendable members
        # (Replicate probes + freshly resumed ones), damped probe rule.
        agree_l2 = agree_lead(agree)
        sendable = (matched_row > 0) | resumed
        rb5 = fwd & member & adv & sendable
        prev5 = jnp.where(matched_row == 0, ts - 1, li_l)
        probe5 = agree_l2 >= prev5
        adopt5 = rb5 & probe5
        retry5 = rb5 & ~probe5 & rev
        state = jnp.where(rb5, ROLE_FOLLOWER, state)
        leader_id = jnp.where(rb5, lead_id_val, leader_id)
        ee = jnp.where(rb5, 0, ee)
        li = jnp.where(adopt5, li_l, li)
        lt = jnp.where(adopt5, lt_l, lt)
        agree = agree_event(
            agree,
            adopt5 | (is_lead & jnp.any(adopt5, axis=0, keepdims=True)),
            li_l,
        )
        li = jnp.where(retry5, li_l, li)
        lt = jnp.where(retry5, lt_l, lt)
        agree = agree_event(
            agree,
            retry5 | (is_lead & jnp.any(retry5, axis=0, keepdims=True)),
            li_l,
        )
        ack5 = (adopt5 & rev) | retry3 | retry5

        # --- wave 6: stage fold over the deferred acks + stage-B commit,
        # then the settled commit propagates to sendable members.
        matched_row = jnp.where(
            ack5, jnp.maximum(matched_row, li_l), matched_row
        )
        ra = ra | ack5
        mci2 = _quorum_tile(matched_row, voter, qpos, P)
        ok_b = has_leader & (count > 0) & (mci2 >= ts)
        c_new2 = jnp.where(ok_b, jnp.maximum(c_new, mci2), c_new)
        commit = jnp.where(is_lead, c_new2, commit)
        agree_l3 = agree_lead(agree)
        sendable2 = (matched_row > 0) | resumed
        elig6 = (
            fwd
            & member
            & sendable2
            & ((agree_l3 >= li_l) | rev)
            & (c_new2 > c_l)
        )
        commit = jnp.where(elig6, jnp.maximum(commit, c_new2), commit)
        ra = ra | (elig6 & rev)

        # --- the round's append workload at the acting leader (nudge
        # cutoffs on its ack stream are provably empty: terms uniform).
        li = li + jnp.where(is_lead, n_app, 0)
        lt = jnp.where(is_lead & sent_b, ltm, lt)
        lead_last = li_l + n_app  # [1, B]
        pr_ok = (matched_row > 0) | resumed
        send_w = sent_b & fwd & member & pr_ok
        agree_l4 = agree_lead(agree)
        probe_w = agree_l4 >= jnp.where(matched_row == 0, ts - 1, li_l)
        sync_b = send_w & (probe_w | rev)
        state = jnp.where(send_w, ROLE_FOLLOWER, state)
        leader_id = jnp.where(send_w, lead_id_val, leader_id)
        ee = jnp.where(send_w, 0, ee)
        li = jnp.where(sync_b, lead_last, li)
        lt = jnp.where(sync_b, ltm, lt)
        ack_w = sync_b & rev
        acked = ack_w | (is_lead & sent_b)
        matched_row = jnp.where(
            acked, jnp.maximum(matched_row, lead_last), matched_row
        )
        ra = ra | ack_w
        agree = agree_event(agree, sync_b | (is_lead & sent_b), lead_last)
        mci3 = _quorum_tile(matched_row, voter, qpos, P)
        ok_c = sent_b & (count > 0) & (mci3 >= ts)
        lead_commit = jnp.where(ok_c, jnp.maximum(c_new2, mci3), c_new2)
        commit = jnp.where(is_lead, lead_commit, commit)
        commit = jnp.where(
            sync_b, jnp.maximum(commit, lead_commit), commit
        )

        if with_health:
            maxc = jnp.max(commit, axis=0, keepdims=True)
            tsc = jnp.where(maxc > maxc_prev, 0, tsc + 1)
            maxc_prev = maxc

    roles_out[...] = _pack_roles(state, leader_id, hb)
    ee_out[...] = ee
    li_out[...] = li
    lt_out[...] = lt
    commit_out[...] = commit
    matched_out[...] = matched_row
    ra_out[...] = ra.astype(jnp.int32)
    agree_out[...] = agree
    if with_health:
        refs[n_in + 8][...] = tsc


def _build_damped_round(
    cfg: SimConfig,
    rounds: int,
    with_health: bool,
    with_counters: bool,
    with_chaos: bool,
    interpret: bool,
    pg_spec,
    g_spec,
    grid,
    block: int,
):
    """The damping-on fused steady round (check_quorum/pre_vote configs):
    see steady_round's docstring.  Separate builder — like the chaos one —
    so the damped machinery cannot perturb the undamped kernels' traced
    graphs (pinned by jaxpr equality in tests/test_pallas_step.py)."""
    P = cfg.n_peers
    G = cfg.n_groups
    assert P <= 15, "packed roles word budgets 4 bits for leader_id"
    assert cfg.heartbeat_tick < (1 << 24), (
        "packed roles word budgets 24 bits for heartbeat_elapsed"
    )
    ppg_spec = pl.BlockSpec(
        (P, P, block), lambda i: (0, 0, i), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _steady_damped_kernel,
        P=P,
        block=block,
        rounds=rounds,
        election_tick=cfg.election_tick,
        heartbeat_tick=cfg.heartbeat_tick,
        with_health=with_health,
        with_cq=cfg.check_quorum,
        with_loss=with_chaos,
    )
    n_ppg_in = 2 if with_chaos else 1
    n_g_in = 3 + (1 if with_chaos else 0) + (1 if with_health else 0)
    out_shape = [jax.ShapeDtypeStruct((P, G), jnp.int32)] * 7 + [
        jax.ShapeDtypeStruct((P, P, G), jnp.int32)
    ]
    out_specs = [pg_spec] * 7 + [ppg_spec]
    if with_health:
        out_shape = out_shape + [jax.ShapeDtypeStruct((1, G), jnp.int32)]
        out_specs = out_specs + [g_spec]
    interp_kw = {"interpret": True} if interpret else {}
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pg_spec] * 8 + [ppg_spec] * n_ppg_in + [g_spec] * n_g_in,
        out_specs=out_specs,
        out_shape=out_shape,
        **interp_kw,
    )

    def _run(
        st: SimState,
        crashed: jnp.ndarray,
        append_n: jnp.ndarray,
        loss_rate: Optional[jnp.ndarray],
        round_base: Optional[jnp.ndarray],
        tsc_in: Optional[jnp.ndarray],
    ):
        if st.recent_active is None:
            raise ValueError(
                "fused damped round needs the recent_active plane but the "
                "state has None — this state was built for an undamped "
                "config; rebuild it with init_state(cfg)"
            )
        is_leader = (st.state == ROLE_LEADER) & ~crashed
        f = is_leader.astype(jnp.int32)
        # dtype= keeps the gathered rows int32 under x64 (GC007).
        acting_row = jnp.sum(
            st.matched * f[:, None, :], axis=0, dtype=jnp.int32
        )  # [P, G]
        ra_row = jnp.any(
            st.recent_active & is_leader[:, None, :], axis=0
        )  # [P, G] bool
        ts_acting = jnp.sum(
            st.term_start_index * f, axis=0, dtype=jnp.int32
        )  # [G]
        lead_term = jnp.sum(st.term * f, axis=0, dtype=jnp.int32)  # [G]
        member = st.voter_mask | st.learner_mask
        # Crashed stale leaders' frozen tracker rows need no carry: the
        # damped wave path's per-round stage folds are idempotent for an
        # owner whose row receives no acks, and every state REACHABLE
        # through that path leaves each stale owner's commit already
        # settled against its frozen row at the round boundary — so k
        # fused rounds that leave them untouched are bit-identical to k
        # general rounds (pinned per configuration in
        # tests/test_pallas_step.py).
        inputs = (
            _pack_roles(st.state, st.leader_id, st.heartbeat_elapsed),
            st.election_elapsed,
            st.last_index,
            st.last_term,
            st.commit,
            acting_row,
            ra_row.astype(jnp.int32),
            _pack_masks(st.voter_mask, member, crashed),
            st.agree,
        )
        if loss_rate is not None:
            inputs = inputs + (loss_rate,)
        inputs = inputs + (
            ts_acting[None, :],
            lead_term[None, :],
            append_n[None, :],
        )
        if round_base is not None:
            rb = jnp.broadcast_to(
                jnp.reshape(round_base.astype(jnp.int32), (1, 1)), (1, G)
            )
            inputs = inputs + (rb,)
        if tsc_in is not None:
            inputs = inputs + (tsc_in[None, :],)
        outs = call(*inputs)
        roles, ee, li, lt, commit, new_row, ra_new, agree = outs[:8]
        tsc_out = outs[8][0] if tsc_in is not None else None
        state, leader_id, hb = _unpack_roles(roles)
        matched = jnp.where(
            is_leader[:, None, :], new_row[None, :, :], st.matched
        )
        recent_active = jnp.where(
            is_leader[:, None, :], (ra_new != 0)[None, :, :],
            st.recent_active,
        )
        out = st._replace(
            state=state,
            leader_id=leader_id,
            election_elapsed=ee,
            heartbeat_elapsed=hb,
            last_index=li,
            last_term=lt,
            matched=matched,
            commit=commit,
            agree=agree,
            recent_active=recent_active,
        )
        return out, tsc_out

    # Static extras layout (counters before health, sim.step's order).
    idx_counters = 0 if with_counters else None
    idx_health = (1 if with_counters else 0) if with_health else None

    def fn(st, crashed, append_n, *rest):
        if with_chaos:  # graftcheck: allow-no-python-branch-on-traced — closes over the static builder flag (trace-time constant)
            loss_rate, round_base = rest[0], rest[1]
            extras = rest[2:]
        else:
            loss_rate = round_base = None
            extras = rest
        counters = None if idx_counters is None else extras[idx_counters]
        health = None if idx_health is None else extras[idx_health]
        tsc_in = None if health is None else health.planes[HP_SINCE_COMMIT]
        out, tsc_out = _run(
            st, crashed, append_n, loss_rate, round_base, tsc_in
        )
        res: tuple = (out,)
        if counters is not None:
            res = res + (_fold_counters(cfg, rounds, st, out, counters),)
        if health is not None:
            res = res + (_steady_health_fold(cfg, rounds, health, tsc_out),)
        if idx_counters is None and idx_health is None:
            return out
        return res

    return fn


def steady_mask(
    cfg: SimConfig,
    st: SimState,
    crashed: jnp.ndarray,
    horizon: int = 1,
    link: Optional[jnp.ndarray] = None,
    reconfig_pending: Optional[jnp.ndarray] = None,
    loss_rate: Optional[jnp.ndarray] = None,
    read_pending: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """bool[G]: per-group steady invariant for the next `horizon` rounds —
    no election timer can fire, exactly one alive leader, every alive peer
    already at the leader's term, not in joint config.

    `reconfig_pending` (optional bool[G] — reconfig.pending_in_horizon:
    groups with a conf entry in flight OR a scheduled op becoming eligible
    within the horizon) is a hard rejection: the fused kernel can neither
    append the conf entry, evaluate the dual-majority commit gate, nor
    swap the mask planes mid-horizon, so any horizon containing a
    scheduled reconfig must take the general path (ISSUE 10; the joint
    window itself is already rejected by the not-joint condition below).
    None keeps every existing graph unchanged.

    With `link` (the chaos engine's bool[P, P, G] reachability plane) the
    invariant additionally requires every directed link among alive peers
    to be up (a fully-healed plane always satisfies this), and the
    election-timer bound falls back to the fully conservative free-running
    form: per-link LOSS may drop any heartbeat, so the per-round re-sync
    that lets the heartbeat_tick == 1 fast bound assume ee -> 0 cannot be
    relied on.

    Election damping (SimConfig.check_quorum / pre_vote) adds its own
    conditions (ISSUE 8; previously damping-on configs were rejected
    wholesale).  The election-timer bound is always the conservative
    free-running form (the same `election_tick > horizon` regime as
    chaos), so the dormancy of pre-vote and the low-term nudge is
    provable: nobody campaigns, terms stay uniform.  With check_quorum
    the leader's election-timeout boundary READS the recent_active row:
    the lossless branch proves every in-horizon boundary passes
    (kernels.cq_boundary_safe — the leader's row holds an active quorum
    NOW, the alive voters re-saturate it each heartbeat interval, and no
    crashed stale leader reaches its boundary); the lossy (`link=`)
    branch cannot prove re-saturation and requires that NO role-leader
    reaches its boundary at all.

    `loss_rate` (optional int32[P, P, G], only meaningful with `link`)
    makes the lossy check-quorum bound PER GROUP (ISSUE 11): a group
    whose loss rates are all zero delivers every heartbeat a healed link
    plane carries, so the LOSSLESS saturation argument
    (kernels.cq_boundary_safe) applies to it even on a chaos horizon;
    only groups with a nonzero rate anywhere keep the conservative
    no-boundary-in-horizon bound.  None preserves the historical
    all-groups conservative form byte-for-byte.

    `read_pending` (optional bool[G] — workload.reads_pending_in_horizon:
    groups with an OUTSTANDING client read, any mode, or a scheduled
    Safe-mode fire inside the horizon) is a hard rejection like
    reconfig_pending (ISSUE 13): the fused kernel can run neither arm of
    the ReadIndex quorum round (the ctx-ack accumulation and the damped
    nudge cutoff are wave logic).  Pure LEASE fires deliberately do NOT
    reject — a lease serve touches no message planes, so a steady horizon
    whose entry gate passes (kernels.lease_read, heartbeat_tick == 1)
    provably serves every in-horizon lease fire at latency 0 and the
    workload split runner folds those receipts closed-form
    (workload.make_split_runner; fused-vs-general bit-parity in
    tests/test_workload.py).  None keeps every existing graph
    unchanged."""
    for flag in planes.steady_defuse_flags():
        # Registry-driven wholesale defuse (planes.py steady == "defuse";
        # today only `blackbox`, ISSUE 15): the fused kernel cannot fold
        # these rows' per-round wave-path writes (the black-box ring
        # trace), so configs enabling them reject every fused horizon and
        # ride the general path; bench.py --blackbox measures the cost,
        # and graphs with every defuse flag off are untouched (this is a
        # python-level branch on static config fields).
        if getattr(cfg, flag):  # graftcheck: allow-no-python-branch-on-traced — `flag` names a static SimConfig bool (registry steady == "defuse"; GC016 pins the field's existence), so this getattr is a trace-time constant
            return jnp.zeros((cfg.n_groups,), bool)
    damped = cfg.check_quorum or cfg.pre_vote
    if damped and cfg.election_tick <= cfg.heartbeat_tick:
        # The check-quorum saturation argument needs one full heartbeat
        # interval strictly inside each boundary window; degenerate
        # configs fall back to the general damped wave path.
        return jnp.zeros((cfg.n_groups,), bool)
    alive = ~crashed
    # 1. nobody can campaign within the horizon.  With heartbeat_tick == 1
    # an alive follower under a live leader is re-synced (ee -> 0) every
    # round, so only its FIRST tick uses the current ee; crashed peers'
    # timers run free for the whole horizon.  For larger heartbeat ticks —
    # and under damping, where free-running timers are what proves
    # pre-vote/nudge dormancy — we fall back to the fully conservative
    # free-running bound.
    non_leader_voter = (st.state != ROLE_LEADER) & st.voter_mask
    if cfg.heartbeat_tick == 1 and link is None and not damped:
        may_fire = non_leader_voter & (
            jnp.where(
                alive,
                st.election_elapsed + 1,
                st.election_elapsed + horizon,
            )
            >= st.randomized_timeout
        )
        # ...and the per-round reset must keep later rounds safe too:
        # 1 tick from a reset timer can never reach rt (rt >= election_tick
        # >= 2 by Config.validate), so no extra condition is needed.
    else:
        may_fire = non_leader_voter & (
            st.election_elapsed + horizon >= st.randomized_timeout
        )
    no_campaign = ~jnp.any(may_fire, axis=0)  # [G]
    # 2. exactly one alive leader per group
    is_leader = (st.state == ROLE_LEADER) & alive
    one_leader = jnp.sum(is_leader.astype(jnp.int32), axis=0) == 1
    # 3. alive peers at the leader's term
    lead_term = jnp.max(jnp.where(is_leader, st.term, 0), axis=0)
    terms_ok = jnp.all(jnp.where(alive, st.term == lead_term, True), axis=0)
    # 4. not joint (the fused kernel computes the single-majority quorum;
    # joint groups take the general XLA path)
    not_joint = ~jnp.any(st.outgoing_mask, axis=0)
    ok = no_campaign & one_leader & terms_ok & not_joint
    if st.transferee is not None:
        # 4b'. no pending leader transfer anywhere in the group (ISSUE
        # 12): the fused kernel can neither pump the catch-up /
        # MsgTimeoutNow protocol nor enforce the transfer's
        # ProposalDropped gate, so a horizon containing one must take
        # the general path.  The transferee plane rides through a fused
        # block untouched (it is provably all-zero here); transfer-off
        # states (transferee=None) keep every existing graph unchanged.
        ok = ok & ~jnp.any(st.transferee > 0, axis=0)
    if reconfig_pending is not None:
        # 4b. no scheduled reconfig touches the horizon (see docstring).
        ok = ok & ~reconfig_pending
    if read_pending is not None:
        # 4c. no quorum-round read work touches the horizon (ISSUE 13;
        # see docstring — lease fires stay fusable and are folded by the
        # caller).
        ok = ok & ~read_pending
    if link is not None:
        # 5. every directed link among alive peers is up (crashed peers'
        # links and self-links are dead weight either way).
        eye = jnp.eye(cfg.n_peers, dtype=bool)[:, :, None]
        links_ok = jnp.all(
            link | eye | crashed[:, None, :] | crashed[None, :, :],
            axis=(0, 1),
        )
        ok = ok & links_ok
    if damped and cfg.check_quorum:
        # 6. every check-quorum boundary inside the horizon provably
        # passes.  Lossless: kernels.cq_boundary_safe (leader row holds
        # an active quorum now; alive voters re-saturate it every
        # heartbeat interval; crashed stale leaders never reach their
        # boundary).  Lossy: a dropped heartbeat breaks the saturation
        # proof, so no role-leader may reach its boundary at all (the
        # conservative free-running bound on the cq boundary).
        if st.recent_active is None:
            raise ValueError(
                "steady_mask for a check_quorum config needs the "
                "recent_active plane but the state has None — this state "
                "was built for an undamped config; rebuild it with "
                "init_state(cfg)"
            )
        if link is None:
            ok = ok & kernels_mod.cq_boundary_safe(
                st.recent_active,
                st.voter_mask,
                st.outgoing_mask,
                st.state,
                crashed,
                st.election_elapsed,
                horizon,
                cfg.election_tick,
            )
        elif loss_rate is not None:
            # Per-group lossy bound (ISSUE 11): only groups with a
            # nonzero loss rate anywhere need the conservative
            # no-boundary form; loss-free groups keep the lossless
            # saturation proof.
            ok = ok & kernels_mod.cq_boundary_safe(
                st.recent_active,
                st.voter_mask,
                st.outgoing_mask,
                st.state,
                crashed,
                st.election_elapsed,
                horizon,
                cfg.election_tick,
                lossy=jnp.any(loss_rate != 0, axis=(0, 1)),
            )
        else:
            role_lead = st.state == ROLE_LEADER
            no_boundary = jnp.all(
                jnp.where(
                    role_lead,
                    st.election_elapsed + jnp.int32(horizon)
                    < jnp.int32(cfg.election_tick),
                    True,
                ),
                axis=0,
            )
            ok = ok & no_boundary
    return ok


def steady_predicate(
    cfg: SimConfig,
    st: SimState,
    crashed: jnp.ndarray,
    horizon: int = 1,
    link: Optional[jnp.ndarray] = None,
    loss_rate: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """True iff EVERY group satisfies the steady invariant (see
    steady_mask)."""
    return jnp.all(
        steady_mask(cfg, st, crashed, horizon, link, loss_rate=loss_rate)
    )


def fast_step(cfg: SimConfig, with_health: bool = False):
    """Dispatcher: the fused pallas round when steady, the general XLA step
    otherwise.  Same signature/semantics as sim.step; with `with_health`
    the fn takes/returns a HealthState extra exactly like sim.step's."""
    pallas_fn = steady_round(cfg, rounds=1, with_health=with_health)

    if with_health:

        def fn_health(st: SimState, crashed, append_n, health):
            pred = steady_predicate(cfg, st, crashed, horizon=1)
            return jax.lax.cond(
                pred,
                lambda args: pallas_fn(*args),
                lambda args: sim_mod.step(
                    cfg, args[0], args[1], args[2], health=args[3]
                ),
                (st, crashed, append_n, health),
            )

        return fn_health

    def fn(st: SimState, crashed, append_n) -> SimState:
        pred = steady_predicate(cfg, st, crashed, horizon=1)
        return jax.lax.cond(
            pred,
            lambda args: pallas_fn(*args),
            lambda args: sim_mod.step(cfg, *args),
            (st, crashed, append_n),
        )

    return fn


def fast_multi_round(
    cfg: SimConfig,
    k: int = 16,
    with_health: bool = False,
    interpret: bool = False,
    with_chaos: bool = False,
    with_counters: bool = False,
    count_fused: bool = False,
):
    """Dispatcher advancing k protocol rounds per call (same crashed/append
    every round): the k-fused pallas kernel when provably steady for the
    whole horizon, else k sequential general steps.  Semantically identical
    to calling sim.step k times.

    With `with_health`, fn(st, crashed, append_n, health) -> (SimState,
    HealthState): both branches thread the health planes, so per-round
    health parity holds whichever branch runs (tests/test_pallas_step.py).

    With `with_counters`, the fn threads the [N_COUNTERS] int32 plane the
    same way (extras order counters-then-health, like sim.step).

    With `with_chaos`, fn(st, crashed, append_n, link, loss_rate,
    round_base, *extras): the link plane and per-link loss rates are the
    chaos engine's fault surface, round_base the absolute round index of
    the first of the k rounds (the loss PRNG replay key).  The fused
    kernel runs when the steady invariant holds AND the link plane is
    fully healed among alive peers (loss is folded in-kernel); otherwise k
    sequential sim.step(link=link & ~loss_draw) rounds run — bit-identical
    either way (tests/test_pallas_step.py).  The chaos predicate feeds the
    loss plane into steady_mask's PER-GROUP check-quorum boundary bound
    (ISSUE 11): loss-free groups keep the lossless saturation proof, so a
    zero-rate chaos overlay no longer forbids in-horizon boundaries.

    With `count_fused`, the fn takes ONE extra trailing int32[] argument —
    the fused GROUP-round accumulator — and returns it (appended last)
    incremented by k * n_groups when the fused branch ran, unchanged
    otherwise.  This is the measured fused-fraction metric (bench.py
    `fused_frac`): an exact in-graph count, not a log line.  int32 bound:
    the caller keeps total group-rounds below 2**31 (bench.py drains it
    per run).  count_fused=False leaves every existing graph unchanged."""
    pallas_fn = steady_round(
        cfg,
        rounds=k,
        with_health=with_health,
        interpret=interpret,
        with_chaos=with_chaos,
        with_counters=with_counters,
    )

    if with_chaos or with_counters:
        n_extra = (1 if with_counters else 0) + (1 if with_health else 0)
        # Static arg layout, resolved at build time: args[3:6] are
        # (link, loss, round_base) when chaos is on; extras follow.
        extras_at = 6 if with_chaos else 3
        chaos_at = 3 if with_chaos else None
        idx_counters = 0 if with_counters else None
        idx_health = (1 if with_counters else 0) if with_health else None

        def slow_general(args):
            st, crashed, append_n = args[:3]
            link = loss = round_base = None
            if chaos_at is not None:
                link, loss, round_base = args[chaos_at : chaos_at + 3]
            extras = args[extras_at:]

            def body(carry, r):
                s, *ex = carry
                kw = {}
                if idx_counters is not None:
                    kw["counters"] = ex[idx_counters]
                if idx_health is not None:
                    kw["health"] = ex[idx_health]
                if link is not None:
                    kw["link"] = link & ~kernels_mod.link_loss_draw(
                        round_base + r, loss
                    )
                res = sim_mod.step(cfg, s, crashed, append_n, **kw)
                # NB: SimState is itself a NamedTuple, so the bare-state
                # return must be wrapped by flag, not isinstance.
                if idx_counters is None and idx_health is None:
                    res = (res,)
                return tuple(res), ()

            carry, _ = jax.lax.scan(
                body,
                (st,) + tuple(extras),
                jnp.arange(k, dtype=jnp.int32),
            )
            return carry if n_extra else carry[0]

        def fast(args):
            st, crashed, append_n = args[:3]
            if chaos_at is None:
                return pallas_fn(st, crashed, append_n, *args[3:])
            loss, round_base = args[4], args[5]
            return pallas_fn(
                st, crashed, append_n, loss, round_base, *args[6:]
            )

        def fn_general(st, crashed, append_n, *rest):
            if count_fused:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
                fused = rest[-1]
                rest = rest[:-1]
            link = rest[0] if chaos_at is not None else None
            loss = rest[1] if chaos_at is not None else None
            pred = steady_predicate(
                cfg, st, crashed, horizon=k, link=link, loss_rate=loss
            )
            out = jax.lax.cond(
                pred,
                fast,
                slow_general,
                (st, crashed, append_n) + tuple(rest),
            )
            if not count_fused:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
                return out
            fused = fused + jnp.where(
                pred, jnp.int32(k * cfg.n_groups), jnp.int32(0)
            )
            if n_extra:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
                return tuple(out) + (fused,)
            return out, fused

        return fn_general

    if with_health:

        def slow_health(args):
            st, crashed, append_n, health = args

            def body(carry, _):
                s, h = carry
                s, h = sim_mod.step(cfg, s, crashed, append_n, health=h)
                return (s, h), ()

            return jax.lax.scan(body, (st, health), None, length=k)[0]

        def fn_health(st: SimState, crashed, append_n, health, *acc):
            pred = steady_predicate(cfg, st, crashed, horizon=k)
            out = jax.lax.cond(
                pred,
                lambda args: pallas_fn(*args),
                slow_health,
                (st, crashed, append_n, health),
            )
            if not count_fused:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
                return out
            fused = acc[0] + jnp.where(
                pred, jnp.int32(k * cfg.n_groups), jnp.int32(0)
            )
            return tuple(out) + (fused,)

        return fn_health

    def slow(args):
        st, crashed, append_n = args

        def body(s, _):
            return sim_mod.step(cfg, s, crashed, append_n), ()

        return jax.lax.scan(body, st, None, length=k)[0]

    def fn(st: SimState, crashed, append_n, *acc):
        pred = steady_predicate(cfg, st, crashed, horizon=k)
        out = jax.lax.cond(
            pred,
            lambda args: pallas_fn(*args),
            slow,
            (st, crashed, append_n),
        )
        if not count_fused:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            return out
        fused = acc[0] + jnp.where(
            pred, jnp.int32(k * cfg.n_groups), jnp.int32(0)
        )
        return out, fused

    return fn


def hybrid_multi_round(
    cfg: SimConfig,
    k: int = 16,
    storm_slots: int = 4096,
    with_chaos: bool = False,
    interpret: bool = False,
    count_fused: bool = False,
):
    """k protocol rounds with a PER-GROUP steady/slow split.

    fast_multi_round drops the ENTIRE batch to k sequential general steps
    when ANY group is non-steady — so one election among 100k groups costs
    the whole batch its ~3-4x fused-kernel advantage.  This dispatcher
    instead gathers the (few) non-steady groups into a fixed-capacity
    [P, storm_slots] sub-batch (static shapes: an argsort permutation, storm
    groups first), advances the sub-batch with k general sim.steps (passing
    global group_ids so each group's (group, term)-keyed timeout PRNG stream
    is unchanged), runs the fused kernel over the full batch, and scatters
    the sub-batch results over the storm groups' (discarded) fused outputs.
    Groups are independent in the lockstep model, so the split is exact —
    bit-identical to k sequential sim.steps (tests/test_pallas_step.py).

    Falls back to k general steps on the whole batch only when more than
    `storm_slots` groups are non-steady (mass storms: elections at boot,
    correlated failures).

    With `with_chaos` (ISSUE 11), the fn signature grows (link, loss_rate,
    round_base) after append_n — the chaos fault surface — and the split
    becomes the per-group answer to the lossy damped boundary problem:
    steady_mask's PER-GROUP check-quorum bound (loss-aware via
    `loss_rate`) decides each group, so only the groups whose boundary
    actually falls inside the horizon (or whose links are faulted) take
    the general branch, while the rest of the batch stays on the fused
    chaos/damped kernel.  Spread boundary phases no longer collapse the
    whole batch to the wave path.  The storm sub-batch passes its global
    group ids into both the timeout PRNG and the per-link loss PRNG
    (kernels.link_loss_draw group_ids=), so every group's seeded streams
    are unchanged — bit-identical to k sequential
    sim.step(link=link & ~loss_draw) rounds.

    With `count_fused`, one extra trailing int32[] accumulator rides the
    signature and returns incremented by k * (fused group count) — the
    per-group fused-fraction metric (group-rounds, exact).

    Health planes are NOT threaded here (use fast_multi_round(...,
    with_health=True) or the general step): the storm split would need a
    per-sub-batch window-position fork that the closed-form steady fold
    cannot express."""
    G = cfg.n_groups
    S = min(storm_slots, G)
    pallas_fn = steady_round(
        cfg, rounds=k, interpret=interpret, with_chaos=with_chaos
    )
    sub_cfg = cfg._replace(n_groups=S)

    def group_mask(st, crashed, link, loss):
        if with_chaos:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            return steady_mask(
                cfg, st, crashed, horizon=k, link=link, loss_rate=loss
            )
        return steady_mask(cfg, st, crashed, horizon=k)

    def slow(args):
        st, crashed, append_n = args[:3]
        if with_chaos:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            link, loss, rb = args[3:6]

            def body_c(s, r):
                lk = link & ~kernels_mod.link_loss_draw(rb + r, loss)
                return sim_mod.step(cfg, s, crashed, append_n, link=lk), ()

            return jax.lax.scan(
                body_c, st, jnp.arange(k, dtype=jnp.int32)
            )[0]

        def body(s, _):
            return sim_mod.step(cfg, s, crashed, append_n), ()

        return jax.lax.scan(body, st, None, length=k)[0]

    def hybrid(args):
        st, crashed, append_n = args[:3]
        link = loss = rb = None
        if with_chaos:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            link, loss, rb = args[3:6]
        mask = group_mask(st, crashed, link, loss)  # [G] True = steady
        # Stable sort: storm groups (False=0) first, original order kept.
        order = jnp.argsort(mask.astype(jnp.int8), stable=True)
        idx = order[:S]  # [S] global ids of the storm groups (+ padding)
        take_sub = ~mask[idx]  # padding entries are steady -> keep fused

        sub = jax.tree.map(lambda a: a[..., idx], st)
        sub_crashed = crashed[:, idx]
        sub_append = append_n[idx]

        if with_chaos:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            sub_link = link[:, :, idx]
            sub_loss = loss[:, :, idx]

            def body_c(s, r):
                # Global group ids key BOTH seeded streams (timeouts and
                # per-link loss), so the gathered replay is bit-identical.
                lk = sub_link & ~kernels_mod.link_loss_draw(
                    rb + r, sub_loss, group_ids=idx.astype(jnp.int32)
                )
                return (
                    sim_mod.step(
                        sub_cfg, s, sub_crashed, sub_append,
                        group_ids=idx, link=lk,
                    ),
                    (),
                )

            sub_out = jax.lax.scan(
                body_c, sub, jnp.arange(k, dtype=jnp.int32)
            )[0]
            fast_out = pallas_fn(st, crashed, append_n, loss, rb)
        else:

            def body(s, _):
                return (
                    sim_mod.step(
                        sub_cfg, s, sub_crashed, sub_append, group_ids=idx
                    ),
                    (),
                )

            sub_out = jax.lax.scan(body, sub, None, length=k)[0]
            fast_out = pallas_fn(st, crashed, append_n)

        def merge(fast, subv):
            gathered = jnp.where(take_sub, subv, fast[..., idx])
            return fast.at[..., idx].set(gathered)

        return jax.tree.map(merge, fast_out, sub_out)

    def pure(args):
        if with_chaos:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            return pallas_fn(args[0], args[1], args[2], args[4], args[5])
        return pallas_fn(*args)

    def fn(st: SimState, crashed, append_n, *rest) -> SimState:
        if count_fused:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            fused = rest[-1]
            rest = rest[:-1]
        link = rest[0] if with_chaos else None
        loss = rest[1] if with_chaos else None
        n_storm = jnp.sum(
            ~group_mask(st, crashed, link, loss)
        ).astype(jnp.int32)
        # Three-way dispatch: the all-steady case takes the PURE fused
        # kernel (no argsort/gather/sub-batch overhead — the common case
        # must cost exactly what fast_multi_round costs), sparse storms the
        # gathered split, mass storms the whole-batch general fallback.
        out = jax.lax.cond(
            n_storm == 0,
            pure,
            lambda args: jax.lax.cond(n_storm <= S, hybrid, slow, args),
            (st, crashed, append_n) + tuple(rest),
        )
        if not count_fused:  # graftcheck: allow-no-python-branch-on-traced — static builder flag
            return out
        fused_groups = jnp.where(
            n_storm <= S, jnp.int32(G) - n_storm, jnp.int32(0)
        )
        return out, fused + jnp.int32(k) * fused_groups

    return fn
