"""Fused Pallas kernels for steady-state MultiRaft rounds.

In the steady state — every group has exactly one alive leader, all alive
peers share its term, and nobody's election timer can fire — a protocol
round touches only {election/heartbeat timers, log tail, matched, commit}.
The XLA expression of that path (sim.step) makes several passes over HBM;
these kernels stream each [P, BLOCK] tile through VMEM once and run **k
whole protocol rounds** on it before writing back, amortizing both HBM
traffic and per-block overhead over k rounds.

Relative shape measured on v5e-1 at 100k groups × 5 peers (steady append
load): at k = 1 the kernel loses to the general XLA step (fusion wins);
at k = 16..32 it is a multiple of the XLA step's throughput.  Absolute
ticks/s on the shared-tunnel TPU varied >2x between measurement windows
(410M-855M across bench rounds), so no single number is quoted here —
current figures come from `python bench.py`, which reports
min/median/max/spread_pct over >=5 repetitions and flags spreads >20%
(see docs/OBSERVABILITY.md).

`steady_predicate(cfg, st, crashed, horizon=k)` decides whether the
invariant provably holds for the next k rounds; `fast_multi_round` then
lax.cond's between the fused kernel and k sequential general steps, so the
fast path is a pure optimization with IDENTICAL semantics
(tests/test_pallas_step.py asserts bit-parity round by round; the crashed
mask and per-round append workload are held constant across the k rounds,
which is exactly the lockstep schedule ScalarCluster/bench drive).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import sim as sim_mod
from .kernels import (
    HP_SINCE_COMMIT,
    HP_TERM_BUMPS,
    HP_VOTE_SPLITS,
    ROLE_LEADER,
)
from .sim import HealthState, SimConfig, SimState

BLOCK = 8192


def _steady_kernel(
    # inputs: state_ref, term_ref, ee_ref, hb_ref, li_ref, lt_ref,
    # matched_ref, commit_ref, voter_ref, member_ref, crashed_ref, ts_ref,
    # app_ref [+ tsc_ref when with_health]; then the outputs: ee, hb, li,
    # lt, matched, commit [+ tsc].  Flat *refs because the health variant
    # adds one input/output pair and pallas kernels take refs positionally.
    *refs,
    P: int,
    rounds: int,
    election_tick: int,
    heartbeat_tick: int,
    with_health: bool,
):
    n_in = 14 if with_health else 13
    (
        state_ref, term_ref, ee_ref, hb_ref, li_ref, lt_ref, matched_ref,
        commit_ref, voter_ref, member_ref, crashed_ref, ts_ref, app_ref,
    ) = refs[:13]
    ee_out, hb_out, li_out, lt_out, matched_out, commit_out = refs[
        n_in : n_in + 6
    ]
    state = state_ref[...]
    term = term_ref[...]
    ee = ee_ref[...]
    hb = hb_ref[...]
    li = li_ref[...]
    lt = lt_ref[...]
    matched = matched_ref[...]
    commit = commit_ref[...]
    voter = voter_ref[...] != 0
    member = member_ref[...] != 0
    crashed = crashed_ref[...] != 0
    term_start = ts_ref[...]  # [1, BLOCK]
    app = app_ref[...]  # [1, BLOCK]
    if with_health:
        tsc = refs[13][...]  # [1, BLOCK] ticks_since_commit plane
        maxc_prev = jnp.max(commit, axis=0, keepdims=True)  # [1, BLOCK]

    alive = ~crashed
    # Timers tick by ROLE — a crashed (isolated) leader keeps ticking
    # (reference: raft.rs:1051-1079; isolation cuts the network, not the
    # clock).  Replication uses the ALIVE leader (exactly one by invariant).
    role_leader = state == ROLE_LEADER  # [P, B]
    is_leader = role_leader & alive
    has_leader = jnp.any(is_leader, axis=0, keepdims=True)  # [1, B]
    # dtype= on every sum in the kernel: a bare jnp.sum widens to int64
    # under x64 — inside a Mosaic kernel that is not even lowerable, and in
    # interpret mode it silently changes the tile dtypes (GC007).
    count = jnp.sum(voter, axis=0, keepdims=True, dtype=jnp.int32)
    qpos = count // 2
    n_app = jnp.where(has_leader, app, 0)  # [1, B]

    for _ in range(rounds):
        # --- tick (reference: raft.rs:1024-1079; no campaigns by invariant)
        ee = ee + 1
        ee = jnp.where(role_leader & (ee >= election_tick), 0, ee)
        hb = jnp.where(role_leader, hb + 1, hb)
        want_beat = role_leader & (hb >= heartbeat_tick)
        hb = jnp.where(want_beat, 0, hb)

        # --- appends at the (unique alive) leader ---
        li = li + jnp.where(is_leader, n_app, 0)
        lt = jnp.where(is_leader, term, lt)
        lead_last = jnp.sum(
            jnp.where(is_leader, li, 0), axis=0, keepdims=True,
            dtype=jnp.int32,
        )
        lead_lt = jnp.sum(
            jnp.where(is_leader, lt, 0), axis=0, keepdims=True,
            dtype=jnp.int32,
        )

        lead_beat = jnp.any(want_beat & is_leader, axis=0, keepdims=True)
        sent = has_leader & (lead_beat | (n_app > 0))  # [1, B]

        # --- instant in-round sync of alive member followers (voters +
        # learners; non-members are outside the progress map) ---
        sync = sent & alive & member & ~is_leader
        ee = jnp.where(sync, 0, ee)
        li = jnp.where(sync, lead_last, li)
        lt = jnp.where(sync, lead_lt, lt)
        matched = jnp.where(sync | (is_leader & sent), li, matched)

        # --- quorum commit via odd-even transposition network over P rows
        # (reference: majority.rs:70-124).  Rows kept 2-D [1, B].
        rows = [
            jnp.where(voter[p : p + 1, :], matched[p : p + 1, :], 0)
            for p in range(P)
        ]
        for pass_ in range(P):
            for i in range(pass_ % 2, P - 1, 2):
                hi = jnp.maximum(rows[i], rows[i + 1])
                lo = jnp.minimum(rows[i], rows[i + 1])
                rows[i], rows[i + 1] = hi, lo
        mci = jnp.zeros_like(rows[0])
        for p in range(P):
            mci = jnp.where(qpos == p, rows[p], mci)

        ok = has_leader & sent & (mci >= term_start)
        lead_commit_old = jnp.sum(
            jnp.where(is_leader, commit, 0), axis=0, keepdims=True,
            dtype=jnp.int32,
        )
        lead_commit = jnp.where(
            ok, jnp.maximum(lead_commit_old, mci), lead_commit_old
        )
        commit = jnp.where((is_leader | sync) & sent, lead_commit, commit)

        if with_health:
            # The one health plane a steady round can move: per-round
            # commit-advance tracking for ticks_since_commit (the other
            # planes are closed-form over a steady horizon — see
            # steady_round's health wrapper).
            maxc = jnp.max(commit, axis=0, keepdims=True)
            tsc = jnp.where(maxc > maxc_prev, 0, tsc + 1)
            maxc_prev = maxc

    ee_out[...] = ee
    hb_out[...] = hb
    li_out[...] = li
    lt_out[...] = lt
    matched_out[...] = matched
    commit_out[...] = commit
    if with_health:
        refs[n_in + 6][...] = tsc


def steady_round(
    cfg: SimConfig,
    rounds: int = 1,
    with_health: bool = False,
    interpret: bool = False,
):
    """Build the pallas_call for `rounds` fused steady protocol rounds;
    returns fn(st, crashed, append_n) -> SimState (same crashed/append each
    round).

    With `with_health`, the returned fn is fn(st, crashed, append_n,
    health) -> (SimState, HealthState), bit-identical to threading
    sim.step's health extra through the same rounds.  Only
    ticks_since_commit needs per-round tracking (one extra [1, BLOCK] VMEM
    plane); the other planes are closed-form over a steady horizon — no
    campaigns can fire and the alive leader holds, so leaderless_ticks
    lands at 0, vote_splits is unchanged, term bumps are 0 and the churn
    window only needs its position advanced (with one reset if a window
    boundary falls inside the horizon)."""
    P = cfg.n_peers
    G = cfg.n_groups
    block = min(BLOCK, G)
    grid = (pl.cdiv(G, block),)

    pg_spec = pl.BlockSpec((P, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    g_spec = pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM)

    kernel = functools.partial(
        _steady_kernel,
        P=P,
        rounds=rounds,
        election_tick=cfg.election_tick,
        heartbeat_tick=cfg.heartbeat_tick,
        with_health=with_health,
    )

    n_g_in = 3 if with_health else 2
    n_out = 7 if with_health else 6
    out_shape = [jax.ShapeDtypeStruct((P, G), jnp.int32)] * 6
    out_specs = [pg_spec] * 6
    if with_health:
        out_shape = out_shape + [jax.ShapeDtypeStruct((1, G), jnp.int32)]
        out_specs = out_specs + [g_spec]
    del n_out

    # `interpret` is for CPU runs with no Mosaic lowering (bench artifact
    # jobs).  Only passed when set: the test fixtures patch pl.pallas_call
    # with setdefault("interpret", True), which an explicit False would
    # defeat.
    interp_kw = {"interpret": True} if interpret else {}
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pg_spec] * 11 + [g_spec] * n_g_in,
        out_specs=out_specs,
        out_shape=out_shape,
        **interp_kw,
    )

    def _run(
        st: SimState,
        crashed: jnp.ndarray,
        append_n: jnp.ndarray,
        tsc_in: Optional[jnp.ndarray],
    ):
        # The acting leader is fixed for the whole steady horizon (no
        # elections, constant crash mask), so its tracker row is gathered
        # once outside the kernel and scattered back after.
        is_leader = (st.state == ROLE_LEADER) & ~crashed
        f = is_leader.astype(jnp.int32)
        # dtype= keeps the gathered tracker rows int32 under x64: these
        # feed pallas_call inputs whose BlockSpecs assume int32 (GC007).
        acting_row = jnp.sum(
            st.matched * f[:, None, :], axis=0, dtype=jnp.int32
        )  # [P, G]
        ts_acting = jnp.sum(
            st.term_start_index * f, axis=0, dtype=jnp.int32
        )  # [G]

        inputs = (
            st.state,
            st.term,
            st.election_elapsed,
            st.heartbeat_elapsed,
            st.last_index,
            st.last_term,
            acting_row,
            st.commit,
            st.voter_mask.astype(jnp.int32),
            (st.voter_mask | st.learner_mask).astype(jnp.int32),
            crashed.astype(jnp.int32),
            ts_acting[None, :],
            append_n[None, :],
        )
        if tsc_in is not None:
            inputs = inputs + (tsc_in[None, :],)
        outs = call(*inputs)
        ee, hb, li, lt, new_row, commit = outs[:6]
        tsc_out = outs[6][0] if tsc_in is not None else None
        matched = jnp.where(
            is_leader[:, None, :], new_row[None, :, :], st.matched
        )
        # Pairwise log-agreement update, applied once for the whole horizon
        # (idempotent per round: the sync set is constant while steady, and
        # only the final leader last_index matters).
        member = st.voter_mask | st.learner_mask
        in_s = (member & ~crashed) | is_leader
        lead_last = jnp.max(jnp.where(is_leader, li, 0), axis=0)  # [G]
        lead_row = jnp.sum(
            st.agree * f[:, None, :], axis=0, dtype=jnp.int32
        )  # [P, G]
        agree = jnp.where(
            in_s[:, None, :] & in_s[None, :, :],
            lead_last[None, None, :],
            jnp.where(
                in_s[:, None, :],
                lead_row[None, :, :],
                jnp.where(in_s[None, :, :], lead_row[:, None, :], st.agree),
            ),
        )
        out = st._replace(
            election_elapsed=ee,
            heartbeat_elapsed=hb,
            last_index=li,
            last_term=lt,
            matched=matched,
            commit=commit,
            agree=agree,
        )
        return out, tsc_out

    def fn(
        st: SimState, crashed: jnp.ndarray, append_n: jnp.ndarray
    ) -> SimState:
        return _run(st, crashed, append_n, None)[0]

    def fn_health(
        st: SimState,
        crashed: jnp.ndarray,
        append_n: jnp.ndarray,
        health: HealthState,
    ):
        out, tsc_out = _run(
            st, crashed, append_n, health.planes[HP_SINCE_COMMIT]
        )
        # Closed-form health fold for a steady horizon (see the docstring):
        # the churn window resets iff a round with window_pos == 0 falls
        # inside [pos, pos + rounds), and every in-horizon bump is 0.
        pos = health.window_pos
        window = jnp.int32(cfg.health_window)
        crossed = (pos == 0) | (pos + jnp.int32(rounds) > window)
        planes = jnp.stack(
            [
                jnp.zeros_like(tsc_out),  # leaderless: a leader held all k
                tsc_out,
                jnp.where(crossed, 0, health.planes[HP_TERM_BUMPS]),
                health.planes[HP_VOTE_SPLITS],
            ]
        )
        new_pos = (pos + jnp.int32(rounds)) % window
        return out, HealthState(planes, new_pos)

    return fn_health if with_health else fn


def steady_mask(
    cfg: SimConfig, st: SimState, crashed: jnp.ndarray, horizon: int = 1
) -> jnp.ndarray:
    """bool[G]: per-group steady invariant for the next `horizon` rounds —
    no election timer can fire, exactly one alive leader, every alive peer
    already at the leader's term, not in joint config."""
    alive = ~crashed
    # 1. nobody can campaign within the horizon.  With heartbeat_tick == 1
    # an alive follower under a live leader is re-synced (ee -> 0) every
    # round, so only its FIRST tick uses the current ee; crashed peers'
    # timers run free for the whole horizon.  For larger heartbeat ticks we
    # fall back to the fully conservative free-running bound.
    non_leader_voter = (st.state != ROLE_LEADER) & st.voter_mask
    if cfg.heartbeat_tick == 1:
        may_fire = non_leader_voter & (
            jnp.where(
                alive,
                st.election_elapsed + 1,
                st.election_elapsed + horizon,
            )
            >= st.randomized_timeout
        )
        # ...and the per-round reset must keep later rounds safe too:
        # 1 tick from a reset timer can never reach rt (rt >= election_tick
        # >= 2 by Config.validate), so no extra condition is needed.
    else:
        may_fire = non_leader_voter & (
            st.election_elapsed + horizon >= st.randomized_timeout
        )
    no_campaign = ~jnp.any(may_fire, axis=0)  # [G]
    # 2. exactly one alive leader per group
    is_leader = (st.state == ROLE_LEADER) & alive
    one_leader = jnp.sum(is_leader.astype(jnp.int32), axis=0) == 1
    # 3. alive peers at the leader's term
    lead_term = jnp.max(jnp.where(is_leader, st.term, 0), axis=0)
    terms_ok = jnp.all(jnp.where(alive, st.term == lead_term, True), axis=0)
    # 4. not joint (the fused kernel computes the single-majority quorum;
    # joint groups take the general XLA path)
    not_joint = ~jnp.any(st.outgoing_mask, axis=0)
    return no_campaign & one_leader & terms_ok & not_joint


def steady_predicate(
    cfg: SimConfig, st: SimState, crashed: jnp.ndarray, horizon: int = 1
) -> jnp.ndarray:
    """True iff EVERY group satisfies the steady invariant (see
    steady_mask)."""
    return jnp.all(steady_mask(cfg, st, crashed, horizon))


def fast_step(cfg: SimConfig, with_health: bool = False):
    """Dispatcher: the fused pallas round when steady, the general XLA step
    otherwise.  Same signature/semantics as sim.step; with `with_health`
    the fn takes/returns a HealthState extra exactly like sim.step's."""
    pallas_fn = steady_round(cfg, rounds=1, with_health=with_health)

    if with_health:

        def fn_health(st: SimState, crashed, append_n, health):
            pred = steady_predicate(cfg, st, crashed, horizon=1)
            return jax.lax.cond(
                pred,
                lambda args: pallas_fn(*args),
                lambda args: sim_mod.step(
                    cfg, args[0], args[1], args[2], health=args[3]
                ),
                (st, crashed, append_n, health),
            )

        return fn_health

    def fn(st: SimState, crashed, append_n) -> SimState:
        pred = steady_predicate(cfg, st, crashed, horizon=1)
        return jax.lax.cond(
            pred,
            lambda args: pallas_fn(*args),
            lambda args: sim_mod.step(cfg, *args),
            (st, crashed, append_n),
        )

    return fn


def fast_multi_round(
    cfg: SimConfig,
    k: int = 16,
    with_health: bool = False,
    interpret: bool = False,
):
    """Dispatcher advancing k protocol rounds per call (same crashed/append
    every round): the k-fused pallas kernel when provably steady for the
    whole horizon, else k sequential general steps.  Semantically identical
    to calling sim.step k times.

    With `with_health`, fn(st, crashed, append_n, health) -> (SimState,
    HealthState): both branches thread the health planes, so per-round
    health parity holds whichever branch runs (tests/test_pallas_step.py).
    """
    pallas_fn = steady_round(
        cfg, rounds=k, with_health=with_health, interpret=interpret
    )

    if with_health:

        def slow_health(args):
            st, crashed, append_n, health = args

            def body(carry, _):
                s, h = carry
                s, h = sim_mod.step(cfg, s, crashed, append_n, health=h)
                return (s, h), ()

            return jax.lax.scan(body, (st, health), None, length=k)[0]

        def fn_health(st: SimState, crashed, append_n, health):
            pred = steady_predicate(cfg, st, crashed, horizon=k)
            return jax.lax.cond(
                pred,
                lambda args: pallas_fn(*args),
                slow_health,
                (st, crashed, append_n, health),
            )

        return fn_health

    def slow(args):
        st, crashed, append_n = args

        def body(s, _):
            return sim_mod.step(cfg, s, crashed, append_n), ()

        return jax.lax.scan(body, st, None, length=k)[0]

    def fn(st: SimState, crashed, append_n) -> SimState:
        pred = steady_predicate(cfg, st, crashed, horizon=k)
        return jax.lax.cond(
            pred,
            lambda args: pallas_fn(*args),
            slow,
            (st, crashed, append_n),
        )

    return fn


def hybrid_multi_round(cfg: SimConfig, k: int = 16, storm_slots: int = 4096):
    """k protocol rounds with a PER-GROUP steady/slow split.

    fast_multi_round drops the ENTIRE batch to k sequential general steps
    when ANY group is non-steady — so one election among 100k groups costs
    the whole batch its ~3-4x fused-kernel advantage.  This dispatcher
    instead gathers the (few) non-steady groups into a fixed-capacity
    [P, storm_slots] sub-batch (static shapes: an argsort permutation, storm
    groups first), advances the sub-batch with k general sim.steps (passing
    global group_ids so each group's (group, term)-keyed timeout PRNG stream
    is unchanged), runs the fused kernel over the full batch, and scatters
    the sub-batch results over the storm groups' (discarded) fused outputs.
    Groups are independent in the lockstep model, so the split is exact —
    bit-identical to k sequential sim.steps (tests/test_pallas_step.py).

    Falls back to k general steps on the whole batch only when more than
    `storm_slots` groups are non-steady (mass storms: elections at boot,
    correlated failures).

    Health planes are NOT threaded here (use fast_multi_round(...,
    with_health=True) or the general step): the storm split would need a
    per-sub-batch window-position fork that the closed-form steady fold
    cannot express."""
    G = cfg.n_groups
    S = min(storm_slots, G)
    pallas_fn = steady_round(cfg, rounds=k)
    sub_cfg = cfg._replace(n_groups=S)

    def slow(args):
        st, crashed, append_n = args

        def body(s, _):
            return sim_mod.step(cfg, s, crashed, append_n), ()

        return jax.lax.scan(body, st, None, length=k)[0]

    def hybrid(args):
        st, crashed, append_n = args
        mask = steady_mask(cfg, st, crashed, horizon=k)  # [G] True = steady
        # Stable sort: storm groups (False=0) first, original order kept.
        order = jnp.argsort(mask.astype(jnp.int8), stable=True)
        idx = order[:S]  # [S] global ids of the storm groups (+ padding)
        take_sub = ~mask[idx]  # padding entries are steady -> keep fused

        sub = jax.tree.map(lambda a: a[..., idx], st)
        sub_crashed = crashed[:, idx]
        sub_append = append_n[idx]

        def body(s, _):
            return (
                sim_mod.step(sub_cfg, s, sub_crashed, sub_append, group_ids=idx),
                (),
            )

        sub_out = jax.lax.scan(body, sub, None, length=k)[0]
        fast_out = pallas_fn(st, crashed, append_n)

        def merge(fast, subv):
            gathered = jnp.where(take_sub, subv, fast[..., idx])
            return fast.at[..., idx].set(gathered)

        return jax.tree.map(merge, fast_out, sub_out)

    def fn(st: SimState, crashed, append_n) -> SimState:
        n_storm = jnp.sum(
            ~steady_mask(cfg, st, crashed, horizon=k)
        ).astype(jnp.int32)
        # Three-way dispatch: the all-steady case takes the PURE fused
        # kernel (no argsort/gather/sub-batch overhead — the common case
        # must cost exactly what fast_multi_round costs), sparse storms the
        # gathered split, mass storms the whole-batch general fallback.
        return jax.lax.cond(
            n_storm == 0,
            lambda args: pallas_fn(*args),
            lambda args: jax.lax.cond(n_storm <= S, hybrid, slow, args),
            (st, crashed, append_n),
        )

    return fn
