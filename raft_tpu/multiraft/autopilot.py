"""Fleet autopilot (ISSUE 12): the closed loop that ACTS on health.

The fleet already elects, replicates, damps, reconfigures, and reports
health under chaos; this module closes ROADMAP item 2's loop: a host-side
DECLARATIVE policy (`AutopilotConfig`: thresholds, per-cadence action
budgets, cooldowns) reads the device-reduced health summary at each drain
cadence and emits batched actions whose ACTUATION is device-resident:

  kick       `sim.step(campaign_kick=)` — RawNode::campaign (MsgHup) at a
             chosen healthy voter of a leaderless group, ending the
             episode at the next cadence instead of waiting out the
             randomized election timeout;
  transfer   `sim.step(transfer_propose=)` — the raft-rs
             MsgTransferLeader / MsgTimeoutNow protocol
             (sim._transfer_phase): moves leadership off an ack-starved
             leader (the asymmetric-partition commit stall that never
             self-heals undamped) and rebalances leader placement against
             skewed workloads ("Paxos vs Raft" names leadership placement
             as THE production lever);
  evacuate   an auto-generated ReconfigPlan (remove the degraded voter,
             add a spare peer) compiled through the PR 10 Changer walk
             and executed by the SAME propose/gate/apply scan as the
             chaos that triggered it — CD-Raft's move-the-group-off-the-
             degraded-site framing.

Execution shape: the chaos horizon runs as cadence-sized donated jitted
segments (`make_cadence_runner` wraps reconfig._runner_body, so the op
protocol, the MTTR/safety folds, and the chaos masks are the SAME code
the reconfig runner uses); between segments the fixed-size health summary
crosses to the host, the policy decides, and the next segment carries the
action planes.  An evacuation decision swaps in the compiled evacuation
schedule for the remaining horizon — reconfig + chaos in one scan.

Determinism/replay: the loop is fully deterministic — identical plans,
state, and policy knobs reproduce identical actions round-for-round (the
device side is the deterministic sim; the policy reads device-computed
summaries only).  `tools/autopilot_report.py` exploits this for the
before/after CI gate: the autopilot-on corpus replay must beat the
autopilot-off replay on MTTR and commit-stall with zero safety
violations.

Since the runner-registry refactor the cadence segment is BUILT by the
unified factory (raft_tpu/multiraft/runner.py) from the schedules.py
registry — :func:`make_cadence_runner` here is a thin behavior-neutral
wrapper, and the flat schedule-arg tuple comes from
``runner.schedule_args`` (GC018 machine-checks both).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Set, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from . import chaos as chaos_mod
from . import kernels
from . import sim as sim_mod
from .reconfig import (
    N_RECONFIG_STATS,
    NO_ROUND,
    CompiledReconfig,
    ReconfigPhase,
    ReconfigPlan,
    compile_plan,
    init_reconfig_state,
)

__all__ = [
    "Autopilot",
    "AutopilotConfig",
    "empty_reconfig_schedule",
    "make_cadence_runner",
]


class AutopilotConfig(NamedTuple):
    """Declarative autopilot policy: thresholds, budgets, cooldowns.

    The policy is HOST-side and pure — it maps one health summary (plus
    on-demand `explain()` columns for the worst offenders) to at most
    `max_*` actions per cadence; actuation is device-resident.
    """

    # Rounds between health reads / action batches (the drain cadence).
    cadence: int = 8
    # Campaign kick: a leaderless group whose HP_LEADERLESS plane is at or
    # over the threshold gets a MsgHup at its best-cursor voter.
    kick: bool = True
    kick_leaderless_ticks: int = 2
    max_kicks: int = 8
    # Leader transfer: a group with an alive leader whose commit has been
    # flat for the threshold gets its leadership transferred to the
    # best-cursor follower voter (the ack-starved-leader heal).
    transfer: bool = True
    transfer_stall_ticks: int = 6
    max_transfers: int = 8
    # Evacuation: when >= evac_min_groups of the inspected worst offenders
    # implicate the SAME degraded voter, those groups' configs are walked
    # off it (remove-voter + add a spare peer) through the PR 10 reconfig
    # protocol.  Off by default: it needs spare peers and is the heaviest
    # action.
    evacuate: bool = False
    evac_stall_ticks: int = 12
    evac_min_groups: int = 2
    # Leader-placement balancing against a skewed workload (the Zipf
    # hot-region regime, benches/suites.py config 3): when on, each
    # cadence ALSO spends up to max_balance_transfers moving the
    # heaviest groups off the most-loaded leader peer onto each group's
    # least-loaded voter — "Paxos vs Raft" names leadership placement as
    # the production lever, and this is its closed-loop form.  Needs the
    # per-group workload weights (run_plan's `append` plane).
    balance: bool = False
    max_balance_transfers: int = 4
    # Rounds before the policy may act on the same group again (actions
    # take a cadence to show up in the health planes).
    cooldown: int = 8

    def validate(self) -> "AutopilotConfig":
        if self.cadence < 1:
            raise ValueError("cadence must be >= 1")
        if self.cooldown < 0:
            raise ValueError("cooldown must be >= 0")
        return self


def empty_reconfig_schedule(
    n_rounds: int, n_peers: int, n_groups: int
) -> CompiledReconfig:
    """A no-op CompiledReconfig spanning `n_rounds`: zero ops, zero extra
    append — composing it with a chaos schedule through _runner_body
    reproduces the plain chaos runner's protocol exactly (the op-protocol
    carry provably never moves).  The autopilot starts every horizon on
    this template and swaps in a real evacuation schedule only when the
    policy fires."""
    P, G = n_peers, n_groups
    return CompiledReconfig(
        phase_of_round=jnp.zeros((n_rounds,), jnp.int32),
        append=jnp.zeros((1, G), jnp.int32),
        op_start=jnp.full((1, G), NO_ROUND, jnp.int32),
        n_ops=jnp.zeros((G,), jnp.int32),
        tgt_voter=jnp.zeros((1, P, G), bool),
        tgt_outgoing=jnp.zeros((1, P, G), bool),
        tgt_learner=jnp.zeros((1, P, G), bool),
        added=jnp.zeros((1, P, G), bool),
        removed=jnp.zeros((1, P, G), bool),
        n_peers=P,
    )


def make_cadence_runner(
    cfg: sim_mod.SimConfig,
    compiled: CompiledReconfig,
    chaos_compiled: Optional[chaos_mod.CompiledChaos],
    rounds: int,
    fused: bool = False,
    interpret: bool = False,
):
    """One jitted cadence segment: `rounds` scan iterations of
    reconfig._runner_body (chaos masks + op protocol + MTTR/safety folds)
    with the autopilot's action planes applied at the segment's FIRST
    round, plus a per-round commit-stall fold (group-rounds at/over
    SimConfig.commit_stall_ticks — the report's second headline metric).

    `fused=True` adds the production fast path (the bench.py --autopilot
    configuration): the whole segment rides the fused Pallas steady
    kernel (pallas_step.steady_round with health + chaos) behind a
    lax.cond whose guard is the steady predicate over the segment horizon
    — which rejects pending transfers and scheduled reconfig ops — AND
    this segment carrying no action (transfer plane all-zero, kick mask
    all-false) with a positive append everywhere (so the closed-form
    commit-stall fold is exactly zero).  Bit-identical to the general
    scan when engaged, like the split runner's fused blocks.

    Signature: (st, hl, rst, stats, rstats, safety, cs_rounds, r0,
    transfer_plane, kick_plane, *schedule_args) with the whole protocol
    carry donated; schedule arrays enter as runtime arguments (GC012).
    Returns the advanced carry (with a trailing fused-group-rounds int32
    scalar accumulated into cs_rounds' sibling position when `fused` —
    callers get it via the returned tuple's last element).

    Thin behavior-neutral wrapper since the runner-registry refactor:
    the construction lives in the unified factory
    (raft_tpu/multiraft/runner.py), instantiated from the schedules.py
    registry — byte-identical jaxpr (GC014 pins it).
    """
    from . import runner as runner_mod

    return runner_mod.make_runner(
        cfg, (compiled, chaos_compiled), cadence=rounds, fused=fused,
        interpret=interpret,
    )


class Autopilot:
    """The closed loop: drive a ClusterSim through a chaos plan in cadence
    segments, reading health and issuing batched heal actions between
    them.  The sim must be built with SimConfig(collect_health=True,
    transfer=True).

    `monitor` (an optional multiraft.health.HealthMonitor) receives the
    per-cadence summaries and the final report; `metrics` (an optional
    raft_tpu.metrics.Metrics) gets `autopilot.action` trace events, the
    multiraft_autopilot_actions_total{kind} counters, and the
    health_groups_transfer_pending gauge.
    """

    def __init__(
        self,
        sim,
        cfg: AutopilotConfig = AutopilotConfig(),
        monitor=None,
        metrics=None,
        fused: bool = False,
        interpret: Optional[bool] = None,
    ):
        self.sim = sim
        self.cfg = cfg.validate()
        self.monitor = (
            monitor
            if monitor is not None
            else getattr(sim, "health_monitor", None)
        )
        self.metrics = metrics
        self.fused = fused
        self.interpret = (
            jax.default_backend() == "cpu" if interpret is None else interpret
        )
        self._cooldown_until: Dict[int, int] = {}
        # Per-group retry counter shared by kicks AND transfers: the
        # policy cannot see liveness, so repeated attempts on the same
        # group rotate through the target ranking instead of
        # deterministically re-picking a dead best-cursor peer forever.
        self._retry_rotation: Dict[int, int] = {}
        self._evacuated: Set[int] = set()
        self._runners: Dict[Tuple, object] = {}
        self.actions_taken = {"kicks": 0, "transfers": 0, "evacuations": 0}

    # --- policy -----------------------------------------------------------

    def _emit(self, kind: str, n: int, round_idx: int, detail) -> None:
        self.actions_taken[kind] += n
        m = self.metrics
        if m is not None and n:
            m.autopilot_actions.labels(kind=kind).inc(n)
            m.trace(
                "autopilot.action", kind=kind, n=n, round=round_idx,
                detail=detail,
            )

    @staticmethod
    def _acting_leader_of(info: dict) -> int:
        """The acting leader from the per-peer role/term columns (state
        == Leader at the highest term, lowest index tie) — NOT from the
        leader_id views, which go stale on partitioned peers (a stale
        view naming an ex-leader would mis-exclude the transfer
        target)."""
        peers = info["peers"]
        best = 0
        best_term = -1
        for p, (role, term) in enumerate(
            zip(peers["state"], peers["term"])
        ):
            if role == kernels.ROLE_LEADER and term > best_term:
                best, best_term = p + 1, term
        return best

    def _ranked_target(
        self, info: dict, exclude: int = 0, attempt: int = 0
    ) -> int:
        """The healthiest-looking VOTER target: ranked by
        (last_index, commit, -peer_id) cursor over the group's voters
        (learners and removed peers are never valid transfer/kick
        targets), skipping `exclude`; `attempt` rotates through the
        ranking across retries — the policy cannot see liveness, and the
        best-looking cursor may belong to the crashed peer."""
        peers = info["peers"]
        voter = peers.get("voter", [True] * len(peers["last_index"]))
        ranked = sorted(
            (
                (-li, -c, p + 1)
                for p, (li, c) in enumerate(
                    zip(peers["last_index"], peers["commit"])
                )
                if p + 1 != exclude and voter[p]
            ),
        )
        if not ranked:
            return 0
        return ranked[attempt % len(ranked)][2]

    def _decide(
        self, summary: dict, round_idx: int
    ) -> Tuple[np.ndarray, np.ndarray, List[dict]]:
        """Map one health summary to this cadence's action planes.
        Returns (transfer[G] int32, kick[P, G] bool, inspected) where
        `inspected` carries each worst offender's explain() columns for
        the evacuation policy (which needs cross-group evidence)."""
        c = self.cfg
        G = self.sim.cfg.n_groups
        P = self.sim.cfg.n_peers
        transfer = np.zeros((G,), np.int32)
        kick = np.zeros((P, G), bool)
        kicks = transfers = 0
        inspected: List[dict] = []
        for w in summary.get("worst", ()):
            g, score = w["group"], w["score"]
            if score <= 0:
                continue
            info = self.sim.explain(g)
            inspected.append(info)
            if self._cooldown_until.get(g, -1) > round_idx:
                continue
            hp = info["health"]
            lead = self._acting_leader_of(info)
            if (
                c.kick
                and kicks < c.max_kicks
                and hp["leaderless_ticks"] >= c.kick_leaderless_ticks
            ):
                attempt = self._retry_rotation.get(g, 0)
                target = self._ranked_target(info, attempt=attempt)
                if target:
                    self._retry_rotation[g] = attempt + 1
                    kick[target - 1, g] = True
                    kicks += 1
                    self._cooldown_until[g] = round_idx + c.cooldown
            elif (
                c.transfer
                and transfers < c.max_transfers
                and lead > 0
                and hp["leaderless_ticks"] == 0
                and hp["ticks_since_commit"] >= c.transfer_stall_ticks
            ):
                attempt = self._retry_rotation.get(g, 0)
                target = self._ranked_target(
                    info, exclude=lead, attempt=attempt
                )
                if target:
                    self._retry_rotation[g] = attempt + 1
                    transfer[g] = target
                    transfers += 1
                    self._cooldown_until[g] = round_idx + c.cooldown
        self._emit("kicks", kicks, round_idx, int(kick.sum()))
        self._emit("transfers", transfers, round_idx,
                   [int(g) for g in np.flatnonzero(transfer)])
        return transfer, kick, inspected

    def balance_transfers(
        self,
        weights=None,
        budget: Optional[int] = None,
        round_idx: int = 0,
        transfer: Optional[np.ndarray] = None,
        crashed=None,
    ) -> np.ndarray:
        """Leader-placement rebalance: greedily move the heaviest groups
        off the most-loaded leader peer onto each group's least-loaded
        OTHER voter, while the move strictly improves the pairwise load
        gap.  Loads are weighted per group (`weights`, default 1s — pass
        the workload's append plane); leader placement comes from the
        device reduction kernels.acting_leader_id, downloaded once
        (int32[G]).  `crashed` (optional bool[P, G]) excludes dead peers
        from the placement read — run_plan passes the upcoming round's
        chaos crash plane so a crashed stale leader is never load-counted
        or picked as a move's src/dst.  Returns the transfer-command
        plane (int32[G]), extending `transfer` if given; budgeted and
        cooldown-aware like every other action."""
        sim = self.sim
        G, P = sim.cfg.n_groups, sim.cfg.n_peers
        budget = (
            self.cfg.max_balance_transfers if budget is None else budget
        )
        out = (
            np.zeros((G,), np.int32) if transfer is None else transfer
        )
        if budget <= 0:
            return out
        if crashed is None:
            crashed = jnp.zeros((P, G), bool)
        # graftcheck: allow-no-host-sync-in-jit — cadence-boundary policy
        # reads (one int32[G] row + the voter masks), outside every
        # jitted segment.
        lead, vm, dead = jax.device_get(
            (
                kernels.acting_leader_id(
                    sim.state.state,
                    sim.state.term,
                    jnp.asarray(crashed, dtype=bool),
                ),
                sim.state.voter_mask,
                jnp.asarray(crashed, dtype=bool),
            )
        )
        if weights is None:
            w = np.ones((G,), np.int64)
        else:
            # graftcheck: allow-no-host-sync-in-jit — host-side policy
            # input (run_plan hands the pre-downloaded workload plane).
            w = np.asarray(weights, np.int64)
        load = np.zeros((P,), np.int64)
        for p in range(P):
            load[p] = int(w[lead == p + 1].sum())
        moves = 0
        moved_groups = []
        # Heaviest groups first: one pass is enough per cadence — the
        # next cadence re-reads placement and continues.
        for g in np.argsort(-w, kind="stable"):
            if moves >= budget:
                break
            src = int(lead[g])
            if src == 0 or out[g]:
                continue
            if self._cooldown_until.get(int(g), -1) > round_idx:
                continue
            others = [
                q + 1
                for q in range(P)
                if vm[q, g] and q + 1 != src and not dead[q, g]
            ]
            if not others:
                continue
            dst = min(others, key=lambda q: (load[q - 1], q))
            # Strict improvement: moving w[g] must shrink the src/dst gap.
            if load[src - 1] - load[dst - 1] <= int(w[g]):
                continue
            out[g] = dst
            load[src - 1] -= int(w[g])
            load[dst - 1] += int(w[g])
            self._cooldown_until[int(g)] = round_idx + self.cfg.cooldown
            moved_groups.append(int(g))
            moves += 1
        self._emit("transfers", moves, round_idx, {"balance": moved_groups})
        return out

    def _decide_evacuation(
        self, inspected: List[dict], round_idx: int, horizon: int
    ) -> Optional[ReconfigPlan]:
        """Cross-group evacuation policy: when enough of the inspected
        worst offenders show the SAME voter lagging far behind its
        group's max cursor, generate the remove+add plan for the affected
        groups (each group is evacuated at most once per run — the
        Changer chain walk starts from the bootstrap config)."""
        c = self.cfg
        if not c.evacuate or round_idx + 2 >= horizon:
            return None
        sim = self.sim
        P = sim.cfg.n_peers
        # graftcheck: allow-no-host-sync-in-jit — cadence-boundary policy
        # read of two [P, G] bool masks, outside every jitted segment.
        vm, lm = jax.device_get(
            (sim.state.voter_mask, sim.state.learner_mask)
        )
        suspects: Dict[int, List[int]] = {}
        for info in inspected:
            g = info["group"]
            if g in self._evacuated:
                continue
            if info["health"]["ticks_since_commit"] < c.evac_stall_ticks:
                continue
            cursors = info["peers"]["commit"]
            hi = max(cursors)
            for p in range(P):
                if vm[p, g] and hi - cursors[p] >= c.evac_stall_ticks:
                    suspects.setdefault(p + 1, []).append(g)
        for peer, groups in sorted(suspects.items()):
            groups = [
                g for g in groups
                if not vm.T[g].all()  # a spare peer must exist
            ]
            if len(groups) < c.evac_min_groups:
                continue
            # One uniform spare for the plan: the lowest peer id outside
            # every selected group's config (bootstrap configs are
            # uniform; per-group spares would need per-group chains).
            spare = 0
            for q in range(1, P + 1):
                if all(
                    not vm[q - 1, g] and not lm[q - 1, g] for g in groups
                ):
                    spare = q
                    break
            if not spare:
                continue
            voters = [p + 1 for p in range(P) if vm[p, groups[0]]]
            learners = [p + 1 for p in range(P) if lm[p, groups[0]]]
            self._evacuated.update(groups)
            self._emit(
                "evacuations", len(groups), round_idx,
                {"peer": peer, "spare": spare, "groups": groups},
            )
            return ReconfigPlan(
                name=f"autopilot-evac-p{peer}",
                n_peers=P,
                voters=voters,
                learners=learners,
                phases=[
                    ReconfigPhase(rounds=round_idx),
                    ReconfigPhase(
                        rounds=1,
                        op={
                            "enter_joint": [
                                {"remove": peer},
                                {"add": spare},
                            ]
                        },
                        groups=groups,
                    ),
                    ReconfigPhase(
                        rounds=horizon - round_idx - 1,
                        op={"leave_joint": True},
                        groups=groups,
                    ),
                ],
            )
        return None

    # --- the loop ---------------------------------------------------------

    def _runner_for(self, compiled, chaos_compiled, rounds: int):
        # Schedule arrays enter the jit as runtime arguments (GC012), so
        # one compiled runner serves every plan with the same SHAPES —
        # the key is shape-only on purpose (an evacuation swap recompiles
        # once, later swaps with the same op count reuse it).
        key = (
            rounds,
            tuple(compiled.op_start.shape),
            tuple(compiled.append.shape),
            compiled.phase_of_round.shape[0],
        )
        r = self._runners.get(key)
        if r is None:
            # The fused fast path only pays off at the full cadence
            # length (a remainder segment would compile its own Pallas
            # kernel for one use).
            r = make_cadence_runner(
                self.sim.cfg, compiled, chaos_compiled, rounds,
                fused=self.fused and rounds == self.cfg.cadence,
                interpret=self.interpret,
            )
            self._runners[key] = r
        return r

    def run_plan(self, chaos_plan=None, append=None) -> dict:
        """Drive the attached sim through `chaos_plan` (default: the
        sim's) with the closed loop ON; returns the autopilot report
        (HealthMonitor.autopilot_report's shape).  The sim's state and
        health planes advance in place, exactly as run_plan would move
        them — plus whatever healing the autopilot achieved.

        `append` (optional int32[G]) is a per-GROUP workload plane ADDED
        to every round's chaos-phase append — the Zipf hot-region
        workload of bench.py --autopilot; None keeps the plan's own
        workload only."""
        sim = self.sim
        scfg = sim.cfg
        G, P = scfg.n_groups, scfg.n_peers
        plan = chaos_plan if chaos_plan is not None else sim._chaos
        if plan is None:
            raise ValueError("no chaos plan; pass one or attach via chaos=")
        if isinstance(plan, chaos_mod.CompiledChaos):
            chaos_compiled = plan
        else:
            chaos_compiled = chaos_mod.compile_plan(plan, G)
        R = chaos_compiled.n_rounds
        compiled = empty_reconfig_schedule(R, P, G)
        append_host = None
        if append is not None:
            # graftcheck: allow-no-host-sync-in-jit — one-time host copy
            # of the caller's workload plane for the balance policy,
            # before any jitted segment runs.
            append_host = np.asarray(append, dtype=np.int64)
            append = jnp.asarray(append, dtype=jnp.int32)
            compiled = compiled._replace(
                append=compiled.append + append[None, :]
            )
        rst = init_reconfig_state(sim.state)
        hl = sim._require_health()
        stats = jnp.zeros((chaos_mod.N_CHAOS_STATS,), jnp.int32)
        rstats = jnp.zeros((N_RECONFIG_STATS,), jnp.int32)
        safety = jnp.zeros((kernels.N_SAFETY,), jnp.int32)
        csr = jnp.int32(0)
        st = sim.state
        bb = sim._blackbox
        transfer = np.zeros((G,), np.int32)
        kick = np.zeros((P, G), bool)
        done = 0
        fused_rounds = 0
        while done < R:
            seg = min(self.cfg.cadence, R - done)
            runner = self._runner_for(compiled, chaos_compiled, seg)
            # The flat runtime-arg tuple comes from the registry
            # (schedules.py via runner.schedule_args) — never hand-listed
            # (GC018).
            from . import runner as runner_mod

            sched_args = runner_mod.schedule_args(compiled, chaos_compiled)
            out = runner(
                st, hl, rst, stats, rstats, safety,
                *((bb,) if bb is not None else ()),
                csr,
                jnp.int32(done),
                jnp.asarray(transfer, dtype=jnp.int32),
                jnp.asarray(kick, dtype=bool),
                *sched_args,
            )
            if bb is not None:
                (
                    st, hl, rst, stats, rstats, safety, bb, csr,
                    seg_fused,
                ) = out
                sim._blackbox = bb
            else:
                st, hl, rst, stats, rstats, safety, csr, seg_fused = out
            if self.fused:
                # graftcheck: allow-no-host-sync-in-jit — one int32
                # scalar per cadence segment, outside the jitted scans.
                fused_rounds += int(jax.device_get(seg_fused))
            sim.state, sim._health = st, hl
            done += seg
            if done >= R:
                break
            # Drain cadence: the fixed-size summary crosses to the host,
            # the policy decides the next segment's action planes.
            summary = sim._health_summary_dict()
            if self.monitor is not None:
                self.monitor.record(summary)
            transfer, kick, inspected = self._decide(summary, done)
            if self.cfg.balance:
                # The upcoming round's crash plane (gathered from the
                # compiled schedule) keeps the placement read honest: a
                # crashed stale leader is neither load-counted nor
                # eligible as a move endpoint.  schedule_planes skips the
                # loss knockout schedule_masks would draw and discard.
                _, _, crash_next, _ = chaos_mod.schedule_planes(
                    chaos_compiled, jnp.int32(done)
                )
                transfer = self.balance_transfers(
                    weights=append_host, round_idx=done,
                    transfer=transfer, crashed=crash_next,
                )
            if self.metrics is not None:
                # graftcheck: allow-no-host-sync-in-jit — one int32
                # scalar at the cadence boundary, outside the segments.
                pending = jax.device_get(
                    jnp.sum(st.transferee > 0, dtype=jnp.int32)
                )
                self.metrics.health_transfer_pending.set(int(pending))
            evac = self._decide_evacuation(inspected, done, R)
            if evac is not None:
                compiled = compile_plan(evac, G)
                if append is not None:
                    compiled = compiled._replace(
                        append=compiled.append + append[None, :]
                    )
                rst = init_reconfig_state(st)
        # Tail audit, exactly make_runner's: a final-round apply's mask
        # transition is checked one extra fold later.
        if bb is not None:
            viol = kernels.check_safety_groups(
                st.state, st.term, st.commit, st.last_index, st.agree,
                st.commit,
                voter_mask=st.voter_mask,
                outgoing_mask=st.outgoing_mask,
                matched=st.matched,
                prev_voter_mask=rst.prev_voter,
                prev_outgoing_mask=rst.prev_outgoing,
            )
            safety = safety + jnp.sum(viol, axis=1, dtype=jnp.int32)
            meta, trip = kernels.blackbox_mark(
                bb.meta, bb.trip_round, bb.round_idx, viol
            )
            sim._blackbox = bb._replace(meta=meta, trip_round=trip)
        else:
            safety = safety + kernels.check_safety(
                st.state, st.term, st.commit, st.last_index, st.agree,
                st.commit,
                voter_mask=st.voter_mask,
                outgoing_mask=st.outgoing_mask,
                matched=st.matched,
                prev_voter_mask=rst.prev_voter,
                prev_outgoing_mask=rst.prev_outgoing,
            )
        from .health import HealthMonitor

        # graftcheck: allow-no-host-sync-in-jit — end-of-run download of
        # fixed-size stat vectors, outside the jitted segments.
        stats_h, safety_h, csr_h = jax.device_get((stats, safety, csr))
        report = HealthMonitor.chaos_report(stats_h, safety_h, R)
        report["commit_stall_group_rounds"] = int(csr_h)
        end = sim._health_summary_dict()
        report["end_counts"] = end["counts"]
        report["actions"] = dict(self.actions_taken)
        if self.fused:
            total = R * G
            report["fused_rounds"] = fused_rounds
            report["total_rounds"] = total
            report["fused_frac"] = round(fused_rounds / total, 4)
        if self.monitor is not None:
            self.monitor.record_autopilot(report)
        return report
