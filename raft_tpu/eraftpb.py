"""Wire format for raft-tpu: the TPU-native re-design of raft-rs's `eraftpb`.

This module is the Python-side equivalent of the reference's protobuf schema
(reference: proto/proto/eraftpb.proto:1-191).  It deliberately keeps the same
*field semantics* (names, meanings, zero-value defaults) so that an application
written against raft-rs can map its transport 1:1, but the in-memory
representation is plain dataclasses: the consensus core never serializes, and
the batched MultiRaft device path uses dense struct-of-arrays tensors instead
of per-message objects (see raft_tpu.multiraft.sim.SimState).

Zero-valued fields mean "absent", matching proto3 semantics the reference
relies on (e.g. `vote == 0` means "voted for nobody", INVALID_ID).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional


class EntryType(enum.IntEnum):
    """reference: proto/proto/eraftpb.proto:7-11"""

    EntryNormal = 0
    EntryConfChange = 1
    EntryConfChangeV2 = 2


class MessageType(enum.IntEnum):
    """The 19 raft message types (reference: proto/proto/eraftpb.proto:49-69).

    MsgHup/MsgBeat/MsgUnreachable/MsgSnapStatus/MsgCheckQuorum are local
    messages that never travel the network (reference: raw_node.rs:57-66).
    """

    MsgHup = 0
    MsgBeat = 1
    MsgPropose = 2
    MsgAppend = 3
    MsgAppendResponse = 4
    MsgRequestVote = 5
    MsgRequestVoteResponse = 6
    MsgSnapshot = 7
    MsgHeartbeat = 8
    MsgHeartbeatResponse = 9
    MsgUnreachable = 10
    MsgSnapStatus = 11
    MsgCheckQuorum = 12
    MsgTransferLeader = 13
    MsgTimeoutNow = 14
    MsgReadIndex = 15
    MsgReadIndexResp = 16
    MsgRequestPreVote = 17
    MsgRequestPreVoteResponse = 18


class ConfChangeTransition(enum.IntEnum):
    """reference: proto/proto/eraftpb.proto:100-116"""

    Auto = 0
    Implicit = 1
    Explicit = 2


class ConfChangeType(enum.IntEnum):
    """reference: proto/proto/eraftpb.proto:133-137"""

    AddNode = 0
    RemoveNode = 1
    AddLearnerNode = 2


@dataclass(slots=True)
class Entry:
    """A single raft log entry (reference: proto/proto/eraftpb.proto:23-33).

    `data` carries the application payload for EntryNormal, or an encoded
    ConfChange/ConfChangeV2 for the conf-change entry types.  `context` is an
    opaque application blob.
    """

    entry_type: EntryType = EntryType.EntryNormal
    term: int = 0
    index: int = 0
    data: bytes = b""
    context: bytes = b""
    sync_log: bool = False  # deprecated; kept for wire parity

    def compute_size(self) -> int:
        """Approximate byte size used for max_size_per_msg accounting.

        The reference uses protobuf's computed size (util.rs:161-179 adds a
        12-byte overhead estimate per entry on top of payload lengths); we use
        the same payload + fixed-overhead model so size-based batching limits
        behave equivalently.
        """
        return len(self.data) + len(self.context)


@dataclass(slots=True)
class ConfState:
    """Membership configuration (reference: proto/proto/eraftpb.proto:118-131)."""

    voters: List[int] = field(default_factory=list)
    learners: List[int] = field(default_factory=list)
    voters_outgoing: List[int] = field(default_factory=list)
    learners_next: List[int] = field(default_factory=list)
    auto_leave: bool = False

    def clone(self) -> "ConfState":
        return ConfState(
            voters=list(self.voters),
            learners=list(self.learners),
            voters_outgoing=list(self.voters_outgoing),
            learners_next=list(self.learners_next),
            auto_leave=self.auto_leave,
        )


def conf_state_eq(lhs: ConfState, rhs: ConfState) -> bool:
    """Order-insensitive ConfState equality (reference: proto/src/confstate.rs:21-40)."""
    return (
        sorted(lhs.voters) == sorted(rhs.voters)
        and sorted(lhs.learners) == sorted(rhs.learners)
        and sorted(lhs.voters_outgoing) == sorted(rhs.voters_outgoing)
        and sorted(lhs.learners_next) == sorted(rhs.learners_next)
        and lhs.auto_leave == rhs.auto_leave
    )


@dataclass(slots=True)
class SnapshotMetadata:
    """reference: proto/proto/eraftpb.proto:35-42"""

    conf_state: ConfState = field(default_factory=ConfState)
    index: int = 0
    term: int = 0


@dataclass(slots=True)
class Snapshot:
    """reference: proto/proto/eraftpb.proto:44-47"""

    data: bytes = b""
    metadata: SnapshotMetadata = field(default_factory=SnapshotMetadata)

    def is_empty(self) -> bool:
        """A snapshot is empty iff its applied index is zero (mirrors the
        reference's `Snapshot::get_metadata().index == 0` convention)."""
        return self.metadata.index == 0

    def clone(self) -> "Snapshot":
        return Snapshot(
            data=self.data,
            metadata=SnapshotMetadata(
                conf_state=self.metadata.conf_state.clone(),
                index=self.metadata.index,
                term=self.metadata.term,
            ),
        )


@dataclass(slots=True)
class Message:
    """A raft protocol message (reference: proto/proto/eraftpb.proto:71-92).

    `from` is a Python keyword, so the field is `from_` (the transport layer
    owns any renaming on the wire).
    """

    msg_type: MessageType = MessageType.MsgHup
    to: int = 0
    from_: int = 0
    term: int = 0
    log_term: int = 0
    index: int = 0
    entries: List[Entry] = field(default_factory=list)
    commit: int = 0
    commit_term: int = 0
    snapshot: Optional[Snapshot] = None
    request_snapshot: int = 0
    reject: bool = False
    reject_hint: int = 0
    context: bytes = b""
    priority: int = 0

    def get_snapshot(self) -> Snapshot:
        if self.snapshot is None:
            self.snapshot = Snapshot()
        return self.snapshot


@dataclass(slots=True)
class HardState:
    """Durable per-node state: {term, vote, commit}
    (reference: proto/proto/eraftpb.proto:94-98)."""

    term: int = 0
    vote: int = 0
    commit: int = 0

    def clone(self) -> "HardState":
        return HardState(self.term, self.vote, self.commit)


@dataclass(slots=True)
class ConfChange:
    """V1 single-step membership change (reference: proto/proto/eraftpb.proto:139-145)."""

    change_type: ConfChangeType = ConfChangeType.AddNode
    node_id: int = 0
    context: bytes = b""
    id: int = 0

    # -- ConfChangeI equivalents (reference: proto/src/confchange.rs) --

    def as_v1(self) -> Optional["ConfChange"]:
        return self

    def as_v2(self) -> "ConfChangeV2":
        return self.into_v2()

    def into_v2(self) -> "ConfChangeV2":
        return ConfChangeV2(
            transition=ConfChangeTransition.Auto,
            changes=[ConfChangeSingle(self.change_type, self.node_id)],
            context=self.context,
        )


@dataclass(slots=True)
class ConfChangeSingle:
    """reference: proto/proto/eraftpb.proto:149-152"""

    change_type: ConfChangeType = ConfChangeType.AddNode
    node_id: int = 0


@dataclass(slots=True)
class ConfChangeV2:
    """Joint-consensus-capable membership change
    (reference: proto/proto/eraftpb.proto:186-190)."""

    transition: ConfChangeTransition = ConfChangeTransition.Auto
    changes: List[ConfChangeSingle] = field(default_factory=list)
    context: bytes = b""

    def as_v1(self) -> Optional[ConfChange]:
        return None

    def as_v2(self) -> "ConfChangeV2":
        return self

    def into_v2(self) -> "ConfChangeV2":
        return self

    def enter_joint(self) -> Optional[bool]:
        """Whether this change should use joint consensus, and if so whether
        it auto-leaves.  Returns None when the simple protocol applies.

        Mirrors the reference's `ConfChangeV2::enter_joint`
        (proto/src/lib.rs): joint consensus is used if there is more than one
        change, or if the transition is explicitly requested (Implicit /
        Explicit on a non-simple change set).
        """
        if (
            self.transition != ConfChangeTransition.Auto
            or len(self.changes) > 1
        ):
            if self.transition in (
                ConfChangeTransition.Auto,
                ConfChangeTransition.Implicit,
            ):
                return True  # auto_leave
            return False
        return None

    def leave_joint(self) -> bool:
        """An empty Auto-transition V2 change is the "leave joint" signal."""
        return self.transition == ConfChangeTransition.Auto and not self.changes


# --- conf-change entry codec ---------------------------------------------
#
# The reference stores protobuf-encoded ConfChange/ConfChangeV2 in
# Entry.data (reference: raft.rs:1995-2012 decodes them in step_leader).
# We use a compact deterministic binary format with the same crucial
# property: a default (empty) ConfChangeV2 encodes to b"", so the
# auto-leave entry appended by commit_apply has zero payload size and can
# never be refused by the uncommitted-size limiter
# (reference: raft.rs:926-935).

import struct as _struct


def encode_conf_change(cc: ConfChange) -> bytes:
    return _struct.pack("<BQQ", int(cc.change_type), cc.node_id, cc.id) + cc.context


def decode_conf_change(data: bytes) -> ConfChange:
    if not data:
        return ConfChange()
    if len(data) < 17:
        raise ValueError("truncated ConfChange")
    change_type, node_id, id = _struct.unpack_from("<BQQ", data, 0)
    return ConfChange(
        change_type=ConfChangeType(change_type),
        node_id=node_id,
        id=id,
        context=data[17:],
    )


def encode_conf_change_v2(cc: ConfChangeV2) -> bytes:
    if (
        cc.transition == ConfChangeTransition.Auto
        and not cc.changes
        and not cc.context
    ):
        return b""
    out = _struct.pack("<BH", int(cc.transition), len(cc.changes))
    for c in cc.changes:
        out += _struct.pack("<BQ", int(c.change_type), c.node_id)
    return out + cc.context


def decode_conf_change_v2(data: bytes) -> ConfChangeV2:
    if not data:
        return ConfChangeV2()
    if len(data) < 3:
        raise ValueError("truncated ConfChangeV2")
    transition, n = _struct.unpack_from("<BH", data, 0)
    off = 3
    changes = []
    for _ in range(n):
        if len(data) < off + 9:
            raise ValueError("truncated ConfChangeV2 changes")
        ct, node_id = _struct.unpack_from("<BQ", data, off)
        changes.append(ConfChangeSingle(ConfChangeType(ct), node_id))
        off += 9
    return ConfChangeV2(
        transition=ConfChangeTransition(transition),
        changes=changes,
        context=data[off:],
    )
