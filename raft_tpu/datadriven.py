"""Golden-file ("datadriven") test runner (reference: datadriven/src/*, a
port of cockroachdb/datadriven — re-designed, not translated).

File format::

    # comment
    cmd key=val key=(v1,v2) positional
    optional input lines
    ----
    expected output

Cases are separated by blank lines.  `run_test(path, handler)` parses each
case, calls `handler(TestData) -> str`, and compares against the recorded
expectation; with rewrite=True (or env RAFT_TPU_REWRITE=1) it regenerates
the file from actual outputs instead (reference: datadriven.rs:151-172's
rewrite mode).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple


@dataclass
class CmdArg:
    """One `key`, `key=val`, or `key=(v1,v2,...)` argument
    (reference: datadriven/src/test_data.rs)."""

    key: str
    vals: List[str] = field(default_factory=list)

    @property
    def value(self) -> str:
        return self.vals[0]


@dataclass
class TestData:
    """One directive block (reference: datadriven/src/test_data.rs:95)."""

    __test__ = False  # not a pytest class despite the name

    pos: str = ""
    cmd: str = ""
    cmd_args: List[CmdArg] = field(default_factory=list)
    input: str = ""
    expected: str = ""
    # The verbatim directive line, kept so rewrite mode reproduces it
    # exactly (recovering it from a text scan mis-fires when a case has no
    # input lines and the scan window drifts into OUTPUT lines).
    directive_line: str = ""

    def arg(self, key: str) -> Optional[CmdArg]:
        for a in self.cmd_args:
            if a.key == key:
                return a
        return None

    def scan_args(self, key: str) -> List[str]:
        a = self.arg(key)
        return a.vals if a else []


def _parse_args(line: str) -> Tuple[str, List[CmdArg]]:
    """Parse `cmd k=v k=(a,b) flag` (reference: datadriven/src/line_sparser.rs)."""
    parts: List[str] = []
    buf = ""
    depth = 0
    for ch in line:
        if ch == "(":
            depth += 1
            buf += ch
        elif ch == ")":
            depth -= 1
            buf += ch
        elif ch.isspace() and depth == 0:
            if buf:
                parts.append(buf)
                buf = ""
        else:
            buf += ch
    if buf:
        parts.append(buf)
    if not parts:
        raise ValueError(f"empty directive line: {line!r}")
    cmd = parts[0]
    args = []
    for p in parts[1:]:
        if "=" in p:
            key, val = p.split("=", 1)
            if val.startswith("(") and val.endswith(")"):
                vals = [v.strip() for v in val[1:-1].split(",") if v.strip()]
            else:
                vals = [val]
            args.append(CmdArg(key=key, vals=vals))
        else:
            args.append(CmdArg(key=p))
    return cmd, args


def parse_file(path: str) -> List[TestData]:
    cases: List[TestData] = []
    with open(path) as f:
        lines = f.readlines()
    i = 0
    n = len(lines)
    while i < n:
        line = lines[i].rstrip("\n")
        if not line.strip() or line.lstrip().startswith("#"):
            i += 1
            continue
        td = TestData(pos=f"{path}:{i + 1}")
        td.cmd, td.cmd_args = _parse_args(line.strip())
        td.directive_line = line.strip()
        i += 1
        # input lines until the ---- separator
        input_lines = []
        while i < n and lines[i].strip() != "----":
            input_lines.append(lines[i].rstrip("\n"))
            i += 1
        td.input = "\n".join(input_lines)
        if i >= n:
            raise ValueError(f"{td.pos}: missing ---- separator")
        i += 1  # skip ----
        expected_lines = []
        while i < n and lines[i].strip() != "":
            expected_lines.append(lines[i].rstrip("\n"))
            i += 1
        td.expected = "\n".join(expected_lines)
        cases.append(td)
    return cases


def _render(td: TestData, output: str) -> str:
    out = [td.directive_line or td.cmd]
    if td.input:
        out.append(td.input)
    out.append("----")
    if output:
        out.append(output.rstrip("\n"))
    return "\n".join(out)


def run_test(
    path: str,
    handler: Callable[[TestData], str],
    rewrite: Optional[bool] = None,
) -> None:
    """Run every case in `path` through `handler`, comparing (or rewriting)
    expectations (reference: datadriven/src/datadriven.rs:91-137)."""
    if rewrite is None:
        rewrite = os.environ.get("RAFT_TPU_REWRITE") == "1"

    cases = parse_file(path)
    outputs = []
    for td in cases:
        outputs.append(handler(td).rstrip("\n"))

    if rewrite:
        blocks = [_render(td, out) for td, out in zip(cases, outputs)]
        with open(path, "w") as f:
            f.write("\n\n".join(blocks) + "\n")
        return

    for td, out in zip(cases, outputs):
        assert out == td.expected, (
            f"{td.pos}: output mismatch for `{td.cmd}`\n"
            f"--- expected ---\n{td.expected}\n--- got ---\n{out}"
        )


def walk(dir: str, handler_for_file: Callable[[str], None]) -> None:
    """Run `handler_for_file` on every .txt under `dir`
    (reference: datadriven/src/lib.rs walk)."""
    for name in sorted(os.listdir(dir)):
        if name.endswith(".txt"):
            handler_for_file(os.path.join(dir, name))
