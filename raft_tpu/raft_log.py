"""Composite log view over stable storage + unstable tail
(reference: src/raft_log.rs).

Invariants (reference: raft_log.rs:44-58):
    applied <= min(committed, persisted)
    persisted < unstable.offset

In the batched MultiRaft path the three cursors live as int arrays
`{committed, persisted, applied}[G]` on device, with entry contents host-side
(SURVEY.md §2 #6).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .errors import Compacted, RaftError, StorageError, Unavailable
from .eraftpb import Entry, Snapshot
from .log_unstable import Unstable
from .storage import Storage
from .util import limit_size

NO_LIMIT = (1 << 64) - 1


class RaftLog:
    __slots__ = (
        "store", "unstable", "committed", "persisted", "applied",
        "on_commit_advance",
    )

    def __init__(self, store: Storage):
        """Initialize cursors from storage (reference: raft_log.rs:79-91)."""
        first_index = store.first_index()
        last_index = store.last_index()
        self.store = store
        self.committed = first_index - 1
        self.persisted = last_index
        self.applied = first_index - 1
        self.unstable = Unstable(last_index + 1)
        # Observability hook: called as (old_committed, new_committed) after
        # every commit_to advance — the single choke point all commit-index
        # growth flows through (raft_tpu.metrics wires this when enabled).
        self.on_commit_advance = None

    def __str__(self) -> str:
        return (
            f"committed={self.committed}, persisted={self.persisted}, "
            f"applied={self.applied}, unstable.offset={self.unstable.offset}, "
            f"unstable.entries.len()={len(self.unstable.entries)}"
        )

    def last_term(self) -> int:
        """reference: raft_log.rs:98-107"""
        return self.term(self.last_index())

    def term(self, idx: int) -> int:
        """Term of the entry at idx; 0 outside the valid range
        (reference: raft_log.rs:122-140).  Raises Compacted/Unavailable when
        the index is in range but the term is not obtainable."""
        dummy_idx = self.first_index() - 1
        if idx < dummy_idx or idx > self.last_index():
            return 0
        t = self.unstable.maybe_term(idx)
        if t is not None:
            return t
        return self.store.term(idx)

    def term_or(self, idx: int, default: int = 0) -> int:
        """`term()` that maps storage errors to a default — the common call
        shape in the reference (`self.term(i).unwrap_or(0)`)."""
        try:
            return self.term(idx)
        except StorageError:
            return default

    def first_index(self) -> int:
        """reference: raft_log.rs:147-152"""
        idx = self.unstable.maybe_first_index()
        if idx is not None:
            return idx
        return self.store.first_index()

    def last_index(self) -> int:
        """reference: raft_log.rs:159-164"""
        idx = self.unstable.maybe_last_index()
        if idx is not None:
            return idx
        return self.store.last_index()

    def find_conflict(self, ents: Sequence[Entry]) -> int:
        """First index where `ents` conflicts with the existing log (same
        index, different term); 0 if fully contained
        (reference: raft_log.rs:182-198)."""
        for e in ents:
            if not self.match_term(e.index, e.term):
                return e.index
        return 0

    def find_conflict_by_term(self, index: int, term: int) -> Tuple[int, Optional[int]]:
        """Largest index with log.term <= term and log.index <= index — the
        fast log rejection probe (reference: raft_log.rs:209-235)."""
        conflict_index = index
        if index > self.last_index():
            return (index, None)
        while True:
            try:
                t = self.term(conflict_index)
            except StorageError:
                return (conflict_index, None)
            if t > term:
                conflict_index -= 1
            else:
                return (conflict_index, t)

    def match_term(self, idx: int, term: int) -> bool:
        """reference: raft_log.rs:238-240"""
        try:
            return self.term(idx) == term
        except StorageError:
            return False

    def maybe_append(
        self, idx: int, term: int, committed: int, ents: Sequence[Entry]
    ) -> Optional[Tuple[int, int]]:
        """Follower append path: returns (conflict_index, last_new_index) on
        success, None if (idx, term) doesn't match our log
        (reference: raft_log.rs:249-279)."""
        if not self.match_term(idx, term):
            return None
        conflict_idx = self.find_conflict(ents)
        if conflict_idx == 0:
            pass
        elif conflict_idx <= self.committed:
            raise AssertionError(
                f"entry {conflict_idx} conflict with committed entry {self.committed}"
            )
        else:
            start = conflict_idx - (idx + 1)
            self.append(ents[start:])
            # Persisted must regress: entries from conflict_idx on changed.
            if self.persisted > conflict_idx - 1:
                self.persisted = conflict_idx - 1
        last_new_index = idx + len(ents)
        self.commit_to(min(committed, last_new_index))
        return (conflict_idx, last_new_index)

    def commit_to(self, to_commit: int) -> None:
        """reference: raft_log.rs:286-300"""
        if self.committed >= to_commit:
            return
        if self.last_index() < to_commit:
            raise AssertionError(
                f"to_commit {to_commit} is out of range [last_index {self.last_index()}]"
            )
        old = self.committed
        self.committed = to_commit
        if self.on_commit_advance is not None:
            self.on_commit_advance(old, to_commit)

    def applied_to(self, idx: int) -> None:
        """Advance the applied cursor (reference: raft_log.rs:309-324).
        Prefer Raft.commit_apply, which runs the joint-consensus on-apply hook."""
        if idx == 0:
            return
        if idx > min(self.committed, self.persisted) or idx < self.applied:
            raise AssertionError(
                f"applied({idx}) is out of range [prev_applied({self.applied}), "
                f"min(committed({self.committed}), persisted({self.persisted}))]"
            )
        self.applied = idx

    def stable_entries(self, index: int, term: int) -> None:
        self.unstable.stable_entries(index, term)

    def stable_snap(self, index: int) -> None:
        self.unstable.stable_snap(index)

    def unstable_entries(self) -> List[Entry]:
        return self.unstable.entries

    def unstable_snapshot(self) -> Optional[Snapshot]:
        return self.unstable.snapshot

    def append(self, ents: Sequence[Entry]) -> int:
        """Append to the unstable tail (reference: raft_log.rs:358-379)."""
        if not ents:
            return self.last_index()
        after = ents[0].index - 1
        if after < self.committed:
            raise AssertionError(
                f"after {after} is out of range [committed {self.committed}]"
            )
        self.unstable.truncate_and_append(list(ents))
        return self.last_index()

    def entries(self, idx: int, max_size: Optional[int] = None) -> List[Entry]:
        """Entries from idx to the end, byte-capped
        (reference: raft_log.rs:382-389)."""
        last = self.last_index()
        if idx > last:
            return []
        return self.slice(idx, last + 1, max_size)

    def all_entries(self) -> List[Entry]:
        """reference: raft_log.rs:392-404"""
        while True:
            first_index = self.first_index()
            try:
                return self.entries(first_index, None)
            except Compacted:
                continue  # racing compaction; retry

    def is_up_to_date(self, last_index: int, term: int) -> bool:
        """Raft §5.4.1 voting check (reference: raft_log.rs:412-414)."""
        return term > self.last_term() or (
            term == self.last_term() and last_index >= self.last_index()
        )

    def next_entries_since(
        self, since_idx: int, max_size: Optional[int] = None
    ) -> Optional[List[Entry]]:
        """Committed AND persisted entries after max(since_idx+1, first_index)
        (reference: raft_log.rs:417-427)."""
        offset = max(since_idx + 1, self.first_index())
        high = min(self.committed, self.persisted) + 1
        if high > offset:
            return self.slice(offset, high, max_size)
        return None

    def next_entries(self, max_size: Optional[int] = None) -> Optional[List[Entry]]:
        """reference: raft_log.rs:432-434"""
        return self.next_entries_since(self.applied, max_size)

    def has_next_entries_since(self, since_idx: int) -> bool:
        """reference: raft_log.rs:438-442"""
        offset = max(since_idx + 1, self.first_index())
        high = min(self.committed, self.persisted) + 1
        return high > offset

    def has_next_entries(self) -> bool:
        return self.has_next_entries_since(self.applied)

    def snapshot(self, request_index: int) -> Snapshot:
        """reference: raft_log.rs:450-457"""
        snap = self.unstable.snapshot
        if snap is not None and snap.metadata.index >= request_index:
            return snap.clone()
        return self.store.snapshot(request_index)

    def pending_snapshot(self) -> Optional[Snapshot]:
        return self.unstable.snapshot

    def _must_check_outofbounds(self, low: int, high: int) -> None:
        """reference: raft_log.rs:463-484; raises Compacted for low < first."""
        if low > high:
            raise AssertionError(f"invalid slice {low} > {high}")
        first_index = self.first_index()
        if low < first_index:
            raise Compacted()
        length = self.last_index() + 1 - first_index
        if high > first_index + length:
            raise AssertionError(
                f"slice[{low},{high}] out of bound[{first_index},{self.last_index()}]"
            )

    def maybe_commit(self, max_index: int, term: int) -> bool:
        """Commit max_index iff it is from the current term — the Raft §5.4.2
        safety rule (reference: raft_log.rs:487-499)."""
        if max_index > self.committed and self.term_or(max_index) == term:
            self.commit_to(max_index)
            return True
        return False

    def maybe_persist(self, index: int, term: int) -> bool:
        """Advance persisted after async persistence completes; never forwards
        past the first not-yet-persisted update (reference: raft_log.rs:502-531,
        incl. the 5-node ABA corner case documented there)."""
        if self.unstable.snapshot is not None:
            first_update_index = self.unstable.snapshot.metadata.index
        else:
            first_update_index = self.unstable.offset
        if index > self.persisted and index < first_update_index:
            try:
                t = self.store.term(index)
            except StorageError:
                return False
            if t == term:
                self.persisted = index
                return True
        return False

    def maybe_persist_snap(self, index: int) -> bool:
        """reference: raft_log.rs:534-561"""
        if index <= self.persisted:
            return False
        if index > self.committed:
            raise AssertionError(
                f"snapshot's index {index} > committed {self.committed}"
            )
        if index >= self.unstable.offset:
            raise AssertionError(
                f"snapshot's index {index} >= offset {self.unstable.offset}"
            )
        self.persisted = index
        return True

    def slice(
        self, low: int, high: int, max_size: Optional[int] = None
    ) -> List[Entry]:
        """Entries in [low, high), byte-capped (reference: raft_log.rs:565-610)."""
        self._must_check_outofbounds(low, high)
        ents: List[Entry] = []
        if low == high:
            return ents

        if low < self.unstable.offset:
            unstable_high = min(high, self.unstable.offset)
            try:
                stored = self.store.entries(low, unstable_high, max_size)
            except Compacted:
                raise
            except Unavailable:
                raise AssertionError(
                    f"entries[{low}:{unstable_high}] is unavailable from storage"
                )
            ents = stored
            if len(ents) < unstable_high - low:
                # Storage byte-capped the result; don't cross into unstable.
                return ents

        if high > self.unstable.offset:
            ents = ents + self.unstable.slice(max(low, self.unstable.offset), high)
        limit_size(ents, max_size)
        return ents

    def restore(self, snapshot: Snapshot) -> None:
        """Reset the log to a snapshot (reference: raft_log.rs:613-634)."""
        index = snapshot.metadata.index
        assert index >= self.committed, f"{index} < {self.committed}"
        # Only persisted entries below `committed` are known-equal to the
        # snapshot's data; regress persisted to committed.
        if self.persisted > self.committed:
            self.persisted = self.committed
        self.committed = index
        self.unstable.restore(snapshot)

    def commit_info(self) -> Tuple[int, int]:
        """reference: raft_log.rs:637-647"""
        try:
            return (self.committed, self.term(self.committed))
        except RaftError as e:
            raise AssertionError(
                f"last committed entry at {self.committed} is missing: {e}"
            )
