"""raft_tpu — a TPU-native multi-Raft consensus framework.

A ground-up re-design of the capabilities of raft-rs (reference:
/root/reference, tikv/raft-rs v0.6.0) for TPU execution:

* **Scalar core** (this package root): the complete Raft consensus module —
  `Raft`, `RawNode`/`Ready`, `RaftLog`, `Storage`/`MemStorage`,
  `ProgressTracker`, quorum math, joint-consensus membership changes,
  linearizable reads — a deterministic, pure function of (state, message),
  bit-exact against the reference's semantics.  This is both a usable
  single-group implementation and the parity oracle for the batched path.

* **Batched MultiRaft path** (`raft_tpu.multiraft`): the per-group hot loop
  (tick timers, quorum commit indices, vote tallies, progress updates) lifted
  into JAX/XLA kernels over `[G, P]` device arrays, sharded across a TPU mesh
  with `shard_map`/`pjit`, advancing tens of thousands of Raft groups in
  lockstep (the BASELINE.json north star).

The application-facing event loop is the Ready protocol, identical in shape
to the reference (reference: lib.rs:176-430): tick()/step()/propose() ->
has_ready() -> ready() -> I/O -> advance() -> advance_apply().
"""

from .config import Config, INVALID_ID, INVALID_INDEX
from .errors import (
    Compacted,
    ConfChangeError,
    ConfigInvalid,
    ProposalDropped,
    RaftError,
    RequestSnapshotDropped,
    SnapshotOutOfDate,
    SnapshotTemporarilyUnavailable,
    StepLocalMsg,
    StepPeerNotFound,
    StorageError,
    Unavailable,
)
from .eraftpb import (
    ConfChange,
    ConfChangeV2,
    ConfChangeSingle,
    ConfChangeTransition,
    ConfChangeType,
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
    conf_state_eq,
)
from .log_unstable import Unstable
from .metrics import EventTracer, Metrics, Registry
from .quorum import JointConfig, MajorityConfig, VoteResult
from .raft import (
    CAMPAIGN_ELECTION,
    CAMPAIGN_PRE_ELECTION,
    CAMPAIGN_TRANSFER,
    Raft,
    SoftState,
    StateRole,
    vote_resp_msg_type,
)
from .raft_log import NO_LIMIT, RaftLog
from .raw_node import (
    LightReady,
    Peer,
    RawNode,
    Ready,
    SnapshotStatus,
    is_local_msg,
)
from .read_only import ReadOnly, ReadOnlyOption, ReadState
from .status import Status
from .storage import (
    ArrayStorage,
    ArrayStorageCore,
    MemStorage,
    MemStorageCore,
    RaftState,
    Storage,
)
from .tracker import (
    Configuration,
    Inflights,
    Progress,
    ProgressState,
    ProgressTracker,
)
from .util import default_logger, majority

__version__ = "0.1.0"

# The "prelude" of the reference (reference: lib.rs:543-570).
__all__ = [
    "Compacted",
    "ConfChangeError",
    "ConfigInvalid",
    "ProposalDropped",
    "RaftError",
    "RequestSnapshotDropped",
    "SnapshotOutOfDate",
    "SnapshotTemporarilyUnavailable",
    "StepLocalMsg",
    "StepPeerNotFound",
    "StorageError",
    "Unavailable",
    "Config",
    "ConfChange",
    "ConfChangeV2",
    "ConfChangeSingle",
    "ConfChangeTransition",
    "ConfChangeType",
    "ConfState",
    "Entry",
    "EntryType",
    "HardState",
    "Message",
    "MessageType",
    "Snapshot",
    "SnapshotMetadata",
    "Raft",
    "RawNode",
    "Ready",
    "LightReady",
    "Peer",
    "SnapshotStatus",
    "RaftLog",
    "Storage",
    "ArrayStorage",
    "ArrayStorageCore",
    "MemStorage",
    "MemStorageCore",
    "RaftState",
    "Unstable",
    "Metrics",
    "Registry",
    "EventTracer",
    "ProgressTracker",
    "Progress",
    "ProgressState",
    "Inflights",
    "Configuration",
    "MajorityConfig",
    "JointConfig",
    "VoteResult",
    "ReadOnly",
    "ReadOnlyOption",
    "ReadState",
    "SoftState",
    "StateRole",
    "Status",
    "majority",
    "default_logger",
    "conf_state_eq",
    "is_local_msg",
    "vote_resp_msg_type",
    "NO_LIMIT",
    "INVALID_ID",
    "INVALID_INDEX",
    "CAMPAIGN_ELECTION",
    "CAMPAIGN_PRE_ELECTION",
    "CAMPAIGN_TRANSFER",
]
