"""ReadOnlyOption enum, split into its own module to avoid a config <-> read_only
import cycle (reference: src/read_only.rs:26-36)."""

from __future__ import annotations

import enum


class ReadOnlyOption(enum.IntEnum):
    """How linearizable reads are served (reference: read_only.rs:26-36)."""

    # Safe: guarantee linearizability by confirming leadership with a quorum
    # round-trip (ReadIndex ctx piggybacked on heartbeats).
    Safe = 0
    # LeaseBased: rely on the leader lease (requires check_quorum); cheaper but
    # affected by clock drift.
    LeaseBased = 1
