"""Error hierarchy for raft-tpu (reference: src/errors.rs:6-109).

The reference models errors as two enums (`Error`, `StorageError`); here they
are an exception hierarchy so both the scalar Python core and the C++ runtime
bindings can raise/translate them uniformly.  Equality (used heavily by the
reference's tests, errors.rs:111-169) compares type + message.
"""

from __future__ import annotations


class RaftError(Exception):
    """Base class for all raft-tpu errors (reference: src/errors.rs:6)."""

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other) and self.args == other.args  # type: ignore[union-attr]

    def __hash__(self) -> int:
        return hash((type(self), self.args))


class Exists(RaftError):
    """The node already exists in the cluster (reference: errors.rs Exists)."""

    def __init__(self, id: int, set: str):
        super().__init__(id, set)
        self.id = id
        self.set = set

    def __str__(self) -> str:
        return f"The node {self.id} already exists in the {self.set} set."


class NotExists(RaftError):
    """The node does not exist in the cluster (reference: errors.rs NotExists)."""

    def __init__(self, id: int, set: str):
        super().__init__(id, set)
        self.id = id
        self.set = set

    def __str__(self) -> str:
        return f"The node {self.id} is not in the {self.set} set."


class ConfChangeError(RaftError):
    """Invalid membership-change request (reference: errors.rs ConfChangeError)."""


class ConfigInvalid(RaftError):
    """Config validation failure (reference: errors.rs ConfigInvalid)."""


class Io(RaftError):
    """IO error wrapper (reference: errors.rs Io)."""


class StepLocalMsg(RaftError):
    """Raft message stepped on a local message type (reference: errors.rs StepLocalMsg)."""

    def __str__(self) -> str:
        return "raft: cannot step raft local message"


class StepPeerNotFound(RaftError):
    """Raft responses dropped: no progress for the peer (reference: errors.rs StepPeerNotFound)."""

    def __str__(self) -> str:
        return "raft: cannot step as peer not found"


class ProposalDropped(RaftError):
    """Proposal was ignored (no leader / transferring / full) (reference: errors.rs ProposalDropped)."""

    def __str__(self) -> str:
        return "raft: proposal dropped"


class RequestSnapshotDropped(RaftError):
    """Follower snapshot request dropped (reference: errors.rs RequestSnapshotDropped)."""

    def __str__(self) -> str:
        return "raft: request snapshot dropped"


class CodecError(RaftError):
    """Serialization/deserialization failure (reference: errors.rs CodecError)."""


# --- Storage errors (reference: src/errors.rs:71-109) ---


class StorageError(RaftError):
    """Base class for storage errors (reference: errors.rs:71)."""


class Compacted(StorageError):
    """Requested log entries are unavailable due to compaction."""

    def __str__(self) -> str:
        return "log compacted"


class Unavailable(StorageError):
    """Requested log entries are unavailable."""

    def __str__(self) -> str:
        return "log unavailable"


class SnapshotOutOfDate(StorageError):
    """Requested snapshot is older than the existing snapshot."""

    def __str__(self) -> str:
        return "snapshot out of date"


class SnapshotTemporarilyUnavailable(StorageError):
    """Snapshot is being generated and not ready yet; retry later."""

    def __str__(self) -> str:
        return "snapshot is temporarily unavailable"
