"""Rebuild a tracker's configuration from a ConfState — used at boot and on
snapshot restore (reference: src/confchange/restore.rs)."""

from __future__ import annotations

from typing import List, Tuple, TYPE_CHECKING

from ..eraftpb import ConfChangeSingle, ConfChangeType, ConfState
from .changer import Changer

if TYPE_CHECKING:
    from ..tracker import ProgressTracker


def to_conf_change_single(
    cs: ConfState,
) -> Tuple[List[ConfChangeSingle], List[ConfChangeSingle]]:
    """Translate a ConfState into (outgoing-ops, incoming-ops): applying the
    outgoing ops to an empty config and then entering joint with the incoming
    ops reproduces the ConfState (reference: restore.rs:14-85)."""
    outgoing = [
        ConfChangeSingle(ConfChangeType.AddNode, id) for id in cs.voters_outgoing
    ]
    incoming: List[ConfChangeSingle] = []
    # Remove all outgoing voters first, then add incoming voters and learners
    # on top (restore.rs:56-83).
    for id in cs.voters_outgoing:
        incoming.append(ConfChangeSingle(ConfChangeType.RemoveNode, id))
    for id in cs.voters:
        incoming.append(ConfChangeSingle(ConfChangeType.AddNode, id))
    for id in cs.learners:
        incoming.append(ConfChangeSingle(ConfChangeType.AddLearnerNode, id))
    for id in cs.learners_next:
        incoming.append(ConfChangeSingle(ConfChangeType.AddLearnerNode, id))
    return outgoing, incoming


def restore(tracker: "ProgressTracker", next_idx: int, cs: ConfState) -> None:
    """Run the change sequence enacting `cs` on an empty tracker
    (reference: restore.rs:91-107)."""
    outgoing, incoming = to_conf_change_single(cs)
    if not outgoing:
        for cc in incoming:
            cfg, changes = Changer(tracker).simple([cc])
            tracker.apply_conf(cfg, changes, next_idx)
    else:
        for cc in outgoing:
            cfg, changes = Changer(tracker).simple([cc])
            tracker.apply_conf(cfg, changes, next_idx)
        cfg, changes = Changer(tracker).enter_joint(cs.auto_leave, incoming)
        tracker.apply_conf(cfg, changes, next_idx)
