"""Joint-consensus membership changes (reference: src/confchange.rs + subdir)."""

from __future__ import annotations

from .changer import Changer, MapChange, MapChangeType, joint
from .restore import restore, to_conf_change_single

__all__ = [
    "Changer",
    "MapChange",
    "MapChangeType",
    "joint",
    "restore",
    "to_conf_change_single",
]
