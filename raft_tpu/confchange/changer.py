"""Validated membership-change transitions (reference: src/confchange/changer.rs).

Host-side by design: conf changes are rare, so the batched MultiRaft path
treats them as per-group barriers that re-materialize the device voter masks
(SURVEY.md §7 hard-part 5).
"""

from __future__ import annotations

import enum
from typing import List, Sequence, Tuple, TYPE_CHECKING

from ..eraftpb import ConfChangeSingle, ConfChangeType
from ..errors import ConfChangeError

if TYPE_CHECKING:
    from ..tracker import Configuration, ProgressMap, ProgressTracker


class MapChangeType(enum.IntEnum):
    """Progress-map delta entry kind (reference: changer.rs:8-11)."""

    Add = 0
    Remove = 1


MapChange = List[Tuple[int, MapChangeType]]


def joint(conf: "Configuration") -> bool:
    """A config is joint iff the outgoing majority is non-empty
    (reference: src/confchange.rs `joint`)."""
    return not conf.voters.outgoing.is_empty()


class IncrChangeMap:
    """Stores progress-map updates instead of applying them directly
    (reference: changer.rs:17-34)."""

    __slots__ = ("changes", "base")

    def __init__(self, base: "ProgressMap"):
        self.changes: MapChange = []
        self.base = base

    def contains(self, id: int) -> bool:
        for i, ct in reversed(self.changes):
            if i == id:
                return ct == MapChangeType.Add
        return id in self.base


class Changer:
    """Validates and computes configuration transitions
    (reference: changer.rs:40-280)."""

    __slots__ = ("tracker",)

    def __init__(self, tracker: "ProgressTracker"):
        self.tracker = tracker

    def enter_joint(
        self, auto_leave: bool, ccs: Sequence[ConfChangeSingle]
    ) -> Tuple["Configuration", MapChange]:
        """Transition (1 2 3)&&() -> (1 2 3 + changes)&&(1 2 3), i.e. into
        C_{new,old} of the Raft thesis §4.3 (reference: changer.rs:66-89)."""
        if joint(self.tracker.conf):
            raise ConfChangeError("config is already joint")
        cfg, prs = self._check_and_copy()
        if cfg.voters.incoming.is_empty():
            raise ConfChangeError("can't make a zero-voter config joint")
        cfg.voters.outgoing.voters.update(cfg.voters.incoming.ids())
        self._apply(cfg, prs, ccs)
        cfg.auto_leave = auto_leave
        check_invariants(cfg, prs)
        return cfg, prs.changes

    def leave_joint(self) -> Tuple["Configuration", MapChange]:
        """Transition C_{new,old} -> C_new: drop the outgoing config and
        promote staged learners (reference: changer.rs:104-129)."""
        if not joint(self.tracker.conf):
            raise ConfChangeError("can't leave a non-joint config")
        cfg, prs = self._check_and_copy()
        if cfg.voters.outgoing.is_empty():
            raise ConfChangeError(f"configuration is not joint: {cfg}")
        cfg.learners.update(cfg.learners_next)
        cfg.learners_next.clear()

        for id in cfg.voters.outgoing.ids():
            if id not in cfg.voters.incoming and id not in cfg.learners:
                prs.changes.append((id, MapChangeType.Remove))

        cfg.voters.outgoing.clear()
        cfg.auto_leave = False
        check_invariants(cfg, prs)
        return cfg, prs.changes

    def simple(self, ccs: Sequence[ConfChangeSingle]) -> Tuple["Configuration", MapChange]:
        """Apply changes mutating the incoming voters by at most one
        (reference: changer.rs:135-157)."""
        if joint(self.tracker.conf):
            raise ConfChangeError("can't apply simple config change in joint config")
        cfg, prs = self._check_and_copy()
        self._apply(cfg, prs, ccs)

        sym_diff = cfg.voters.incoming.ids() ^ self.tracker.conf.voters.incoming.ids()
        if len(sym_diff) > 1:
            raise ConfChangeError(
                "more than one voter changed without entering joint config"
            )
        check_invariants(cfg, prs)
        return cfg, prs.changes

    # --- internals (reference: changer.rs:162-279) ---

    def _apply(
        self,
        cfg: "Configuration",
        prs: IncrChangeMap,
        ccs: Sequence[ConfChangeSingle],
    ) -> None:
        for cc in ccs:
            if cc.node_id == 0:
                # node_id zero means "change elided downstream"; skip.
                continue
            if cc.change_type == ConfChangeType.AddNode:
                self._make_voter(cfg, prs, cc.node_id)
            elif cc.change_type == ConfChangeType.AddLearnerNode:
                self._make_learner(cfg, prs, cc.node_id)
            else:
                self._remove(cfg, prs, cc.node_id)
        if cfg.voters.incoming.is_empty():
            raise ConfChangeError("removed all voters")

    def _make_voter(self, cfg: "Configuration", prs: IncrChangeMap, id: int) -> None:
        if not prs.contains(id):
            self._init_progress(cfg, prs, id, is_learner=False)
            return
        cfg.voters.incoming.voters.add(id)
        cfg.learners.discard(id)
        cfg.learners_next.discard(id)

    def _make_learner(self, cfg: "Configuration", prs: IncrChangeMap, id: int) -> None:
        if not prs.contains(id):
            self._init_progress(cfg, prs, id, is_learner=True)
            return
        if id in cfg.learners:
            return
        cfg.voters.incoming.voters.discard(id)
        cfg.learners.discard(id)
        cfg.learners_next.discard(id)
        # A voter still present in the outgoing config is only *staged* as a
        # learner (learners_next) to preserve voter/learner disjointness.
        if id in cfg.voters.outgoing:
            cfg.learners_next.add(id)
        else:
            cfg.learners.add(id)

    def _remove(self, cfg: "Configuration", prs: IncrChangeMap, id: int) -> None:
        if not prs.contains(id):
            return
        cfg.voters.incoming.voters.discard(id)
        cfg.learners.discard(id)
        cfg.learners_next.discard(id)
        # Keep the Progress while the peer is still an outgoing voter.
        if id not in cfg.voters.outgoing:
            prs.changes.append((id, MapChangeType.Remove))

    def _init_progress(
        self, cfg: "Configuration", prs: IncrChangeMap, id: int, is_learner: bool
    ) -> None:
        if not is_learner:
            cfg.voters.incoming.voters.add(id)
        else:
            cfg.learners.add(id)
        prs.changes.append((id, MapChangeType.Add))

    def _check_and_copy(self) -> Tuple["Configuration", IncrChangeMap]:
        prs = IncrChangeMap(self.tracker.progress)
        check_invariants(self.tracker.conf, prs)
        return self.tracker.conf.clone(), prs


def check_invariants(cfg: "Configuration", prs: IncrChangeMap) -> None:
    """Config/progress compatibility invariants (reference: changer.rs:285-355)."""
    for id in cfg.voters.ids():
        if not prs.contains(id):
            raise ConfChangeError(f"no progress for voter {id}")
    for id in cfg.learners:
        if not prs.contains(id):
            raise ConfChangeError(f"no progress for learner {id}")
        if id in cfg.voters.outgoing:
            raise ConfChangeError(f"{id} is in learners and outgoing voters")
        if id in cfg.voters.incoming:
            raise ConfChangeError(f"{id} is in learners and incoming voters")
    for id in cfg.learners_next:
        if not prs.contains(id):
            raise ConfChangeError(f"no progress for learner(next) {id}")
        if id not in cfg.voters.outgoing:
            raise ConfChangeError(f"{id} is in learners_next and outgoing voters")
    if not joint(cfg):
        if cfg.learners_next:
            raise ConfChangeError("learners_next must be empty when not joint")
        if cfg.auto_leave:
            raise ConfChangeError("auto_leave must be false when not joint")
