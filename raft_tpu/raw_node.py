"""RawNode: the event-loop facade over the raft state machine
(reference: src/raw_node.rs).

Implements the Ready protocol: the application calls tick()/step()/propose(),
harvests a `Ready` when has_ready(), performs I/O in the documented order
(send messages -> apply snapshot -> apply committed entries -> append entries
-> persist HardState -> send persisted messages), then advance()s.  Readys are
numbered and their persistence effects applied in order via ReadyRecords,
enabling the async variant (advance_append_async + on_persist_ready) that
decouples fsync from the state machine — the precedent for the MultiRaft
driver overlapping device steps with host persistence.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple, Union

from .config import Config
from .errors import RaftError, StepLocalMsg, StepPeerNotFound
from .eraftpb import (
    ConfChange,
    ConfChangeV2,
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    encode_conf_change,
    encode_conf_change_v2,
)
from .raft import Raft, SoftState, StateRole
from .read_only import ReadState
from .status import Status
from .storage import Storage


@dataclass
class Peer:
    """A peer in the cluster (reference: raw_node.rs:39-45)."""

    id: int = 0
    context: Optional[bytes] = None


class SnapshotStatus:
    """reference: raw_node.rs:48-54"""

    Finish = 0
    Failure = 1


def is_local_msg(t: MessageType) -> bool:
    """Message types that never travel the network
    (reference: raw_node.rs:57-66)."""
    return t in (
        MessageType.MsgHup,
        MessageType.MsgBeat,
        MessageType.MsgUnreachable,
        MessageType.MsgSnapStatus,
        MessageType.MsgCheckQuorum,
    )


def is_response_msg(t: MessageType) -> bool:
    """reference: raw_node.rs:68-77"""
    return t in (
        MessageType.MsgAppendResponse,
        MessageType.MsgRequestVoteResponse,
        MessageType.MsgHeartbeatResponse,
        MessageType.MsgUnreachable,
        MessageType.MsgRequestPreVoteResponse,
    )


@dataclass
class LightReady:
    """Commit index + committed entries + messages that become valid after
    the previous Ready is persisted (reference: raw_node.rs:242-282)."""

    commit_index: Optional[int] = None
    committed_entries: List[Entry] = field(default_factory=list)
    messages: List[Message] = field(default_factory=list)

    def take_committed_entries(self) -> List[Entry]:
        ents, self.committed_entries = self.committed_entries, []
        return ents

    def take_messages(self) -> List[Message]:
        msgs, self.messages = self.messages, []
        return msgs


@dataclass
class Ready:
    """The outstanding work the application must handle
    (reference: raw_node.rs:88-227)."""

    number: int = 0
    ss: Optional[SoftState] = None
    hs: Optional[HardState] = None
    read_states: List[ReadState] = field(default_factory=list)
    entries: List[Entry] = field(default_factory=list)
    snapshot: Snapshot = field(default_factory=Snapshot)
    is_persisted_msg: bool = False
    light: LightReady = field(default_factory=LightReady)
    # must_sync is False iff (no HardState change beyond commit) and (no
    # entries or snapshot); False permits async HardState writes
    # (reference: raw_node.rs:218-227).
    must_sync: bool = False

    def committed_entries(self) -> List[Entry]:
        return self.light.committed_entries

    def take_committed_entries(self) -> List[Entry]:
        return self.light.take_committed_entries()

    def messages(self) -> List[Message]:
        """Messages sendable immediately (leader pipelining, thesis 10.2.1)."""
        return [] if self.is_persisted_msg else self.light.messages

    def take_messages(self) -> List[Message]:
        return [] if self.is_persisted_msg else self.light.take_messages()

    def persisted_messages(self) -> List[Message]:
        """Messages to send only AFTER persisting hs/entries/snapshot."""
        return self.light.messages if self.is_persisted_msg else []

    def take_persisted_messages(self) -> List[Message]:
        return self.light.take_messages() if self.is_persisted_msg else []

    def take_read_states(self) -> List[ReadState]:
        rs, self.read_states = self.read_states, []
        return rs

    def take_entries(self) -> List[Entry]:
        ents, self.entries = self.entries, []
        return ents


@dataclass
class ReadyRecord:
    """Persistence bookkeeping for one numbered Ready
    (reference: raw_node.rs:231-237)."""

    number: int
    last_entry: Optional[Tuple[int, int]] = None  # (index, term)
    snapshot: Optional[Tuple[int, int]] = None  # (index, term)


class RawNode:
    """Thread-unsafe node facade (reference: raw_node.rs:287-761)."""

    def __init__(self, config: Config, store: Storage):
        """reference: raw_node.rs:302-321"""
        assert config.id != 0, "config.id must not be zero"
        self.raft = Raft(config, store)
        self.prev_ss = SoftState()
        self.prev_hs = HardState()
        self.max_number = 0
        self.records: Deque[ReadyRecord] = deque()
        self.commit_since_index = config.applied
        self.prev_hs = self.raft.hard_state()
        self.prev_ss = self.raft.soft_state()

    def set_priority(self, priority: int) -> None:
        self.raft.set_priority(priority)

    def tick(self) -> bool:
        """Advance the logical clock one tick (reference: raw_node.rs:342-344)."""
        return self.raft.tick()

    def campaign(self) -> None:
        """reference: raw_node.rs:347-351"""
        self.raft.step(Message(msg_type=MessageType.MsgHup))

    def propose(self, context: bytes, data: bytes) -> None:
        """Propose appending data to the log (reference: raw_node.rs:354-363)."""
        m = Message(
            msg_type=MessageType.MsgPropose,
            from_=self.raft.id,
            entries=[Entry(data=data, context=context)],
        )
        self.raft.step(m)

    def ping(self) -> None:
        self.raft.ping()

    def propose_conf_change(
        self, context: bytes, cc: Union[ConfChange, ConfChangeV2]
    ) -> None:
        """Propose a config change; with auto_leave the caller must still
        propose the empty change to exit joint state
        (reference: raw_node.rs:378-392)."""
        if cc.as_v1() is not None:
            data = encode_conf_change(cc)  # type: ignore[arg-type]
            ty = EntryType.EntryConfChange
        else:
            data = encode_conf_change_v2(cc.as_v2())
            ty = EntryType.EntryConfChangeV2
        m = Message(
            msg_type=MessageType.MsgPropose,
            entries=[Entry(entry_type=ty, data=data, context=context)],
        )
        self.raft.step(m)

    def apply_conf_change(
        self, cc: Union[ConfChange, ConfChangeV2]
    ) -> ConfState:
        """reference: raw_node.rs:397-399"""
        return self.raft.apply_conf_change(cc.as_v2())

    def step(self, m: Message) -> None:
        """Feed an inbound network message (reference: raw_node.rs:402-411)."""
        if is_local_msg(m.msg_type):
            raise StepLocalMsg()
        if self.raft.prs.get(m.from_) is not None or not is_response_msg(m.msg_type):
            return self.raft.step(m)
        raise StepPeerNotFound()

    def _gen_light_ready(self) -> LightReady:
        """reference: raw_node.rs:414-434"""
        rd = LightReady()
        max_size = self.raft.max_committed_size_per_ready
        ents = self.raft.raft_log.next_entries_since(
            self.commit_since_index, max_size
        )
        rd.committed_entries = ents if ents is not None else []
        self.raft.reduce_uncommitted_size(rd.committed_entries)
        if rd.committed_entries:
            last = rd.committed_entries[-1]
            assert self.commit_since_index < last.index
            self.commit_since_index = last.index
        if self.raft.msgs:
            rd.messages, self.raft.msgs = self.raft.msgs, []
        return rd

    def ready(self) -> Ready:
        """Harvest the pending work; MUST be fully handled then passed back
        via advance (reference: raw_node.rs:444-516)."""
        raft = self.raft

        self.max_number += 1
        rd = Ready(number=self.max_number)
        rd_record = ReadyRecord(number=self.max_number)

        if (
            self.prev_ss.raft_state != StateRole.Leader
            and raft.state == StateRole.Leader
        ):
            # Becoming leader implies everything before was persisted (the
            # vote that elected us was sent post-persist), and candidate
            # records can't carry entries/snapshots.
            for record in self.records:
                assert record.last_entry is None
                assert record.snapshot is None
            self.records.clear()

        ss = raft.soft_state()
        if ss != self.prev_ss:
            rd.ss = ss
        hs = raft.hard_state()
        if hs != self.prev_hs:
            if hs.vote != self.prev_hs.vote or hs.term != self.prev_hs.term:
                rd.must_sync = True
            rd.hs = hs

        if raft.read_states:
            rd.read_states, raft.read_states = raft.read_states, []

        snapshot = raft.raft_log.unstable_snapshot()
        if snapshot is not None:
            rd.snapshot = snapshot.clone()
            assert self.commit_since_index <= rd.snapshot.metadata.index
            self.commit_since_index = rd.snapshot.metadata.index
            # A pending snapshot implies no committed entries after it.
            assert not raft.raft_log.has_next_entries_since(
                self.commit_since_index
            ), f"has snapshot but also has committed entries since {self.commit_since_index}"
            rd_record.snapshot = (
                rd.snapshot.metadata.index,
                rd.snapshot.metadata.term,
            )
            rd.must_sync = True

        rd.entries = list(raft.raft_log.unstable_entries())
        if rd.entries:
            e = rd.entries[-1]
            rd.must_sync = True
            rd_record.last_entry = (e.index, e.term)

        # Leaders pipeline: their messages don't wait for persistence
        # (thesis 10.2.1; reference: raw_node.rs:510-512).
        rd.is_persisted_msg = raft.state != StateRole.Leader
        rd.light = self._gen_light_ready()
        self.records.append(rd_record)
        if raft.metrics is not None:
            raft.metrics.on_ready(rd.must_sync)
        return rd

    def has_ready(self) -> bool:
        """reference: raw_node.rs:519-552"""
        raft = self.raft
        if raft.msgs:
            return True
        if raft.soft_state() != self.prev_ss:
            return True
        if raft.hard_state() != self.prev_hs:
            return True
        if raft.read_states:
            return True
        if raft.raft_log.unstable_entries():
            return True
        snap = self.snap()
        if snap is not None and not snap.is_empty():
            return True
        if raft.raft_log.has_next_entries_since(self.commit_since_index):
            return True
        return False

    def _commit_ready(self, rd: Ready) -> None:
        """reference: raw_node.rs:554-570"""
        if self.raft.metrics is not None:
            self.raft.metrics.on_advance()
        if rd.ss is not None:
            self.prev_ss = rd.ss
        if rd.hs is not None:
            self.prev_hs = rd.hs
        rd_record = self.records[-1]
        assert rd_record.number == rd.number
        raft = self.raft
        if rd_record.snapshot is not None:
            raft.raft_log.stable_snap(rd_record.snapshot[0])
        if rd_record.last_entry is not None:
            index, term = rd_record.last_entry
            raft.raft_log.stable_entries(index, term)

    def _commit_apply(self, applied: int) -> None:
        self.raft.commit_apply(applied)

    def on_persist_ready(self, number: int) -> None:
        """All readies numbered <= `number` are persisted
        (reference: raw_node.rs:583-609)."""
        index, term = 0, 0
        snap_index = 0
        while self.records:
            record = self.records[0]
            if record.number > number:
                break
            self.records.popleft()
            if record.snapshot is not None:
                snap_index = record.snapshot[0]
                index, term = 0, 0
            if record.last_entry is not None:
                index, term = record.last_entry
        if snap_index != 0:
            self.raft.on_persist_snap(snap_index)
        if index != 0:
            self.raft.on_persist_entries(index, term)

    def advance(self, rd: Ready) -> LightReady:
        """Advance after fully processing `rd` (persist + apply + send)
        (reference: raw_node.rs:620-625)."""
        applied = self.commit_since_index
        light_rd = self.advance_append(rd)
        self.advance_apply_to(applied)
        return light_rd

    def advance_append(self, rd: Ready) -> LightReady:
        """Advance without applying; implies everything so far is persisted
        (reference: raw_node.rs:635-653)."""
        self._commit_ready(rd)
        self.on_persist_ready(self.max_number)
        light_rd = self._gen_light_ready()
        if self.raft.state != StateRole.Leader and light_rd.messages:
            raise AssertionError("not leader but has new msg after advance")
        hard_state = self.raft.hard_state()
        if hard_state.commit > self.prev_hs.commit:
            light_rd.commit_index = hard_state.commit
            self.prev_hs.commit = hard_state.commit
        else:
            assert hard_state.commit == self.prev_hs.commit
            light_rd.commit_index = None
        assert hard_state == self.prev_hs, "hard state != prev_hs"
        return light_rd

    def advance_append_async(self, rd: Ready) -> None:
        """Cache-only advance; call on_persist_ready when fsync completes
        (reference: raw_node.rs:663-665)."""
        self._commit_ready(rd)

    def advance_apply(self) -> None:
        """reference: raw_node.rs:669-671"""
        self._commit_apply(self.commit_since_index)

    def advance_apply_to(self, applied: int) -> None:
        """reference: raw_node.rs:675-677"""
        self._commit_apply(applied)

    def snap(self) -> Optional[Snapshot]:
        return self.raft.snap()

    def status(self) -> Status:
        """reference: raw_node.rs:687-689"""
        return Status.new(self.raft)

    def report_unreachable(self, id: int) -> None:
        """reference: raw_node.rs:692-698"""
        try:
            self.raft.step(Message(msg_type=MessageType.MsgUnreachable, from_=id))
        except RaftError:
            pass

    def report_snapshot(self, id: int, status: int) -> None:
        """reference: raw_node.rs:701-709"""
        rej = status == SnapshotStatus.Failure
        try:
            self.raft.step(
                Message(msg_type=MessageType.MsgSnapStatus, from_=id, reject=rej)
            )
        except RaftError:
            pass

    def request_snapshot(self, request_index: int) -> None:
        """reference: raw_node.rs:713-715"""
        self.raft.request_snapshot(request_index)

    def transfer_leader(self, transferee: int) -> None:
        """reference: raw_node.rs:718-723"""
        try:
            self.raft.step(
                Message(msg_type=MessageType.MsgTransferLeader, from_=transferee)
            )
        except RaftError:
            pass

    def read_index(self, rctx: bytes) -> None:
        """Request a linearizable read state (reference: raw_node.rs:729-736)."""
        try:
            self.raft.step(
                Message(
                    msg_type=MessageType.MsgReadIndex,
                    entries=[Entry(data=rctx)],
                )
            )
        except RaftError:
            pass

    @property
    def store(self) -> Storage:
        return self.raft.store

    def skip_bcast_commit(self, skip: bool) -> None:
        self.raft.set_skip_bcast_commit(skip)

    def set_batch_append(self, batch_append: bool) -> None:
        self.raft.set_batch_append(batch_append)
