"""The raft consensus state machine (reference: src/raft.rs).

This is the scalar per-group core: roles and elections (with pre-vote,
priority, and check-quorum leases), log replication with flow control,
snapshot send/receive, joint-consensus hooks, leader transfer (thesis 3.10),
uncommitted-size backpressure, batched appends, fast log-rejection probing,
follower-requested snapshots, and commit-by-vote fast-forward.

It is deliberately a pure function of (state, message) — no clock, no I/O,
no randomness other than the injected counter-based timeout PRNG — which is
what makes it usable as the bit-exact parity oracle for the batched TPU path
(raft_tpu.multiraft): same message schedule in, identical commit indices out.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from .config import Config
from .confchange import Changer, joint as conf_is_joint, restore as confchange_restore
from .errors import ProposalDropped, RaftError, RequestSnapshotDropped, SnapshotTemporarilyUnavailable, StorageError
from .eraftpb import (
    ConfChangeV2,
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    conf_state_eq,
    decode_conf_change,
    decode_conf_change_v2,
)
from .quorum import VoteResult
from .raft_log import RaftLog
from .read_only import ReadOnly, ReadOnlyOption, ReadState
from .storage import Storage
from .tracker import ProgressState, ProgressTracker
from .util import NO_LIMIT, deterministic_timeout, is_continuous_ents

logger = logging.getLogger("raft_tpu")

# Campaign types (reference: raft.rs:48-57).
CAMPAIGN_PRE_ELECTION = b"CampaignPreElection"
CAMPAIGN_ELECTION = b"CampaignElection"
CAMPAIGN_TRANSFER = b"CampaignTransfer"

INVALID_ID = 0
INVALID_INDEX = 0


class StateRole:
    """The role of the node (reference: raft.rs:61-70).  Plain int codes so
    the MultiRaft path mirrors them as a uint8 array."""

    Follower = 0
    Candidate = 1
    Leader = 2
    PreCandidate = 3

    _NAMES = {0: "Follower", 1: "Candidate", 2: "Leader", 3: "PreCandidate"}

    @classmethod
    def name(cls, v: int) -> str:
        return cls._NAMES[v]


@dataclass
class SoftState:
    """Volatile state useful for logging/UX (reference: raft.rs:86-91)."""

    leader_id: int = INVALID_ID
    raft_state: int = StateRole.Follower


class UncommittedState:
    """Uncommitted-proposal byte accounting on the leader
    (reference: raft.rs:95-157)."""

    __slots__ = ("max_uncommitted_size", "uncommitted_size", "last_log_tail_index")

    def __init__(self, max_uncommitted_size: int):
        self.max_uncommitted_size = max_uncommitted_size
        self.uncommitted_size = 0
        self.last_log_tail_index = 0

    def is_no_limit(self) -> bool:
        return self.max_uncommitted_size == NO_LIMIT

    def maybe_increase_uncommitted_size(self, ents: Sequence[Entry]) -> bool:
        """reference: raft.rs:114-134"""
        if self.is_no_limit():
            return True
        size = sum(len(e.data) for e in ents)
        # Never drop zero-size entries (elections, auto-leave), always allow
        # at least one uncommitted entry.
        if (
            size == 0
            or self.uncommitted_size == 0
            or size + self.uncommitted_size <= self.max_uncommitted_size
        ):
            self.uncommitted_size += size
            return True
        return False

    def maybe_reduce_uncommitted_size(self, ents: Sequence[Entry]) -> bool:
        """reference: raft.rs:136-156"""
        if self.is_no_limit() or not ents:
            return True
        # Entries from before this node became leader don't count.
        size = sum(
            len(e.data) for e in ents if e.index > self.last_log_tail_index
        )
        if size > self.uncommitted_size:
            self.uncommitted_size = 0
            return False
        self.uncommitted_size -= size
        return True


def new_message(to: int, msg_type: MessageType, from_: Optional[int] = None) -> Message:
    """reference: raft.rs:296-304"""
    m = Message(msg_type=msg_type, to=to)
    if from_ is not None:
        m.from_ = from_
    return m


def vote_resp_msg_type(t: MessageType) -> MessageType:
    """reference: raft.rs:307-313"""
    if t == MessageType.MsgRequestVote:
        return MessageType.MsgRequestVoteResponse
    if t == MessageType.MsgRequestPreVote:
        return MessageType.MsgRequestPreVoteResponse
    raise ValueError(f"Not a vote message: {t!r}")


class Raft:
    """The raft consensus state machine (reference: raft.rs:163-294 for the
    field inventory; one class here instead of the Raft/RaftCore split, which
    only exists to appease the Rust borrow checker)."""

    def __init__(self, c: Config, store: Storage):
        """reference: raft.rs:318-400"""
        c.validate()
        raft_state = store.initial_state()
        conf_state = raft_state.conf_state

        self.id = c.id
        self.term = 0
        self.vote = INVALID_ID
        self.read_states: List[ReadState] = []
        self.raft_log = RaftLog(store)
        self.max_inflight = c.max_inflight_msgs
        self.max_msg_size = c.max_size_per_msg
        self.pending_request_snapshot = INVALID_INDEX
        self.state = StateRole.Follower
        self.promotable = False
        self.leader_id = INVALID_ID
        self.lead_transferee: Optional[int] = None
        self.pending_conf_index = 0
        self.read_only = ReadOnly(c.read_only_option)
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self.check_quorum = c.check_quorum
        self.pre_vote = c.pre_vote
        self.skip_bcast_commit = c.skip_bcast_commit
        self.batch_append = c.batch_append
        self.heartbeat_timeout = c.heartbeat_tick
        self.election_timeout = c.election_tick
        self.randomized_election_timeout = 0
        self.min_election_timeout = c.min_election_tick_or_default()
        self.max_election_timeout = c.max_election_tick_or_default()
        self.priority = c.priority
        self.uncommitted_state = UncommittedState(c.max_uncommitted_size)
        self.max_committed_size_per_ready = c.max_committed_size_per_ready
        # Counter-based timeout PRNG key (see util.deterministic_timeout).
        self._timeout_key = c.timeout_seed * (1 << 16) + c.id
        # Observability plane (raft_tpu.metrics.Metrics) or None; every hook
        # below is guarded by one `is not None` branch so the disabled path
        # stays free.  timeout_seed doubles as the group tag (the MultiRaft
        # driver's per-group convention).
        self.metrics = c.metrics
        self._group = c.timeout_seed
        if self.metrics is not None:
            self.raft_log.on_commit_advance = self._on_commit_advance

        self.prs = ProgressTracker(c.max_inflight_msgs)
        self.msgs: List[Message] = []

        confchange_restore(self.prs, self.raft_log.last_index(), conf_state)
        new_cs = self.post_conf_change()
        if not conf_state_eq(new_cs, conf_state):
            raise AssertionError(f"invalid restore: {conf_state} != {new_cs}")

        if raft_state.hard_state != HardState():
            self.load_state(raft_state.hard_state)
        if c.applied > 0:
            self.commit_apply(c.applied)
        self.become_follower(self.term, INVALID_ID)

    def _on_commit_advance(self, old: int, new: int) -> None:
        """RaftLog.commit_to observability callback (metrics enabled only)."""
        # graftcheck: allow-metrics-guarded — the hook is registered in
        # __init__ only when metrics is not None, so the callback cannot
        # fire on the disabled path; re-checking here would add the very
        # branch the invariant exists to avoid.
        self.metrics.on_commit_advance(self._group, self.id, self.term, old, new)

    # --- accessors (reference: raft.rs:402-598) ---

    @property
    def store(self) -> Storage:
        return self.raft_log.store

    def snap(self) -> Optional[Snapshot]:
        return self.raft_log.unstable.snapshot

    def pending_read_count(self) -> int:
        return self.read_only.pending_read_count()

    def ready_read_count(self) -> int:
        return len(self.read_states)

    def soft_state(self) -> SoftState:
        return SoftState(leader_id=self.leader_id, raft_state=self.state)

    def hard_state(self) -> HardState:
        return HardState(
            term=self.term, vote=self.vote, commit=self.raft_log.committed
        )

    def in_lease(self) -> bool:
        """reference: raft.rs:464-466"""
        return self.state == StateRole.Leader and self.check_quorum

    def set_priority(self, priority: int) -> None:
        self.priority = priority

    def set_randomized_election_timeout(self, t: int) -> None:
        """Test hook pinning the randomized timeout (reference: raft.rs:470-473)."""
        assert self.min_election_timeout <= t < self.max_election_timeout
        self.randomized_election_timeout = t

    def set_skip_bcast_commit(self, skip: bool) -> None:
        self.skip_bcast_commit = skip

    def set_batch_append(self, batch_append: bool) -> None:
        self.batch_append = batch_append

    def set_max_committed_size_per_ready(self, size: int) -> None:
        self.max_committed_size_per_ready = size

    # --- group commit (reference: raft.rs:507-576) ---

    def enable_group_commit(self, enable: bool) -> None:
        self.prs.enable_group_commit(enable)
        if self.state == StateRole.Leader and not enable and self.maybe_commit():
            self.bcast_append()

    def group_commit(self) -> bool:
        return self.prs.group_commit()

    def assign_commit_groups(self, ids: Sequence[Tuple[int, int]]) -> None:
        for peer_id, group_id in ids:
            assert group_id > 0
            pr = self.prs.get_mut(peer_id)
            if pr is not None:
                pr.commit_group_id = group_id
        if (
            self.state == StateRole.Leader
            and self.group_commit()
            and self.maybe_commit()
        ):
            self.bcast_append()

    def clear_commit_group(self) -> None:
        for _, pr in self.prs.iter_mut():
            pr.commit_group_id = 0

    def check_group_commit_consistent(self) -> Optional[bool]:
        """reference: raft.rs:557-576"""
        if self.state != StateRole.Leader:
            return None
        if not self.apply_to_current_term():
            return None
        index, use_group_commit = self.prs.maximal_committed_index()
        return use_group_commit and index == self.raft_log.committed

    def commit_to_current_term(self) -> bool:
        """reference: raft.rs:581-585"""
        return self.raft_log.term_or(self.raft_log.committed) == self.term

    def apply_to_current_term(self) -> bool:
        """reference: raft.rs:588-592"""
        return self.raft_log.term_or(self.raft_log.applied) == self.term

    # --- message sending (reference: raft.rs:600-845) ---

    def send(self, m: Message) -> None:
        """Stamp the term per message-type rules and queue for the transport
        (reference: raft.rs:602-662)."""
        if m.from_ == INVALID_ID:
            m.from_ = self.id
        if m.msg_type in (
            MessageType.MsgRequestVote,
            MessageType.MsgRequestPreVote,
            MessageType.MsgRequestVoteResponse,
            MessageType.MsgRequestPreVoteResponse,
        ):
            # Campaign messages carry an explicit term: possibly a future one
            # for pre-vote rounds.
            if m.term == 0:
                raise AssertionError(
                    f"term should be set when sending {m.msg_type!r}"
                )
        else:
            if m.term != 0:
                raise AssertionError(
                    f"term should not be set when sending {m.msg_type!r} "
                    f"(was {m.term})"
                )
            # MsgPropose / MsgReadIndex are forwarded to the leader and act
            # as local messages — never stamp a term on them.
            if m.msg_type not in (
                MessageType.MsgPropose,
                MessageType.MsgReadIndex,
            ):
                m.term = self.term
        if m.msg_type in (
            MessageType.MsgRequestVote,
            MessageType.MsgRequestPreVote,
        ):
            m.priority = self.priority
        if self.metrics is not None:
            self.metrics.on_send(m.msg_type)
        self.msgs.append(m)

    def _prepare_send_snapshot(self, m: Message, pr, to: int) -> bool:
        """reference: raft.rs:664-712"""
        if not pr.recent_active:
            return False
        m.msg_type = MessageType.MsgSnapshot
        try:
            snapshot = self.raft_log.snapshot(pr.pending_request_snapshot)
        except SnapshotTemporarilyUnavailable:
            return False
        if snapshot.metadata.index == 0:
            raise AssertionError("need non-empty snapshot")
        m.snapshot = snapshot
        pr.become_snapshot(snapshot.metadata.index)
        if self.metrics is not None:
            self.metrics.on_snapshot_sent(
                self._group, self.id, to, snapshot.metadata.index
            )
        return True

    def _prepare_send_entries(
        self, m: Message, pr, term: int, ents: List[Entry]
    ) -> None:
        """reference: raft.rs:714-730"""
        m.msg_type = MessageType.MsgAppend
        m.index = pr.next_idx - 1
        m.log_term = term
        m.entries = ents
        m.commit = self.raft_log.committed
        if m.entries:
            pr.update_state(m.entries[-1].index)

    def _try_batching(self, to: int, pr, ents: List[Entry]) -> bool:
        """Coalesce into an existing queued MsgAppend for the same peer
        (reference: raft.rs:732-760)."""
        for msg in self.msgs:
            if msg.msg_type == MessageType.MsgAppend and msg.to == to:
                if ents:
                    if not is_continuous_ents(msg.entries, ents):
                        return False
                    msg.entries = msg.entries + ents
                    pr.update_state(msg.entries[-1].index)
                msg.commit = self.raft_log.committed
                return True
        return False

    def send_append(self, to: int) -> None:
        """reference: raft.rs:764-766, 850-853"""
        pr = self.prs.get_mut(to)
        if pr is not None:
            self._maybe_send_append(to, pr, allow_empty=True)

    def _maybe_send_append(self, to: int, pr, allow_empty: bool) -> bool:
        """reference: raft.rs:773-819"""
        if pr.is_paused():
            return False
        m = Message(to=to)
        if pr.pending_request_snapshot != INVALID_INDEX:
            # The follower explicitly asked for a snapshot.
            if not self._prepare_send_snapshot(m, pr, to):
                return False
        else:
            try:
                ents: Optional[List[Entry]] = self.raft_log.entries(
                    pr.next_idx, self.max_msg_size
                )
            except StorageError:
                ents = None
            if not allow_empty and not ents:
                return False
            try:
                term: Optional[int] = self.raft_log.term(pr.next_idx - 1)
            except StorageError:
                term = None
            if term is not None and ents is not None:
                if self.batch_append and self._try_batching(to, pr, ents):
                    return True
                self._prepare_send_entries(m, pr, term, ents)
            else:
                # Entries compacted away: fall back to a snapshot.
                if not self._prepare_send_snapshot(m, pr, to):
                    return False
        self.send(m)
        return True

    def _send_heartbeat(self, to: int, pr, ctx: Optional[bytes]) -> None:
        """reference: raft.rs:822-844; commit is clamped to min(matched,
        committed) so an unmatched follower never learns a commit index it
        doesn't have."""
        m = Message(to=to, msg_type=MessageType.MsgHeartbeat)
        m.commit = min(pr.matched, self.raft_log.committed)
        if ctx is not None:
            m.context = ctx
        self.send(m)

    def bcast_append(self) -> None:
        """reference: raft.rs:857-865"""
        for id, pr in self.prs.iter_mut():
            if id == self.id:
                continue
            self._maybe_send_append(id, pr, allow_empty=True)

    def ping(self) -> None:
        """reference: raft.rs:868-872"""
        if self.state == StateRole.Leader:
            self.bcast_heartbeat()

    def bcast_heartbeat(self) -> None:
        """reference: raft.rs:875-878"""
        self._bcast_heartbeat_with_ctx(self.read_only.last_pending_request_ctx())

    def _bcast_heartbeat_with_ctx(self, ctx: Optional[bytes]) -> None:
        for id, pr in self.prs.iter_mut():
            if id == self.id:
                continue
            self._send_heartbeat(id, pr, ctx)

    # --- commit machinery (reference: raft.rs:891-939) ---

    def maybe_commit(self) -> bool:
        """Advance the commit index from the quorum of matched indexes; the
        caller broadcasts on True (reference: raft.rs:893-904)."""
        mci, _ = self.prs.maximal_committed_index()
        if self.raft_log.maybe_commit(mci, self.term):
            pr = self.prs.get_mut(self.id)
            if pr is not None:
                pr.update_committed(self.raft_log.committed)
            return True
        return False

    def commit_apply(self, applied: int) -> None:
        """Register the applied index; post-hook auto-leaves a joint config
        (reference: raft.rs:913-939)."""
        old_applied = self.raft_log.applied
        self.raft_log.applied_to(applied)

        if (
            self.prs.conf.auto_leave
            and old_applied <= self.pending_conf_index
            and applied >= self.pending_conf_index
            and self.state == StateRole.Leader
        ):
            # Propose the empty ConfChangeV2 that exits the joint config;
            # empty data can never be refused by the size limiter.
            entry = Entry(entry_type=EntryType.EntryConfChangeV2)
            if not self.append_entry([entry]):
                raise AssertionError(
                    "appending an empty EntryConfChangeV2 should never be dropped"
                )
            self.pending_conf_index = self.raft_log.last_index()

    def reset(self, term: int) -> None:
        """reference: raft.rs:942-971"""
        if self.term != term:
            self.term = term
            self.vote = INVALID_ID
        self.leader_id = INVALID_ID
        self.reset_randomized_election_timeout()
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self.abort_leader_transfer()
        self.prs.reset_votes()
        self.pending_conf_index = 0
        self.read_only = ReadOnly(self.read_only.option)
        self.pending_request_snapshot = INVALID_INDEX

        last_index = self.raft_log.last_index()
        committed = self.raft_log.committed
        persisted = self.raft_log.persisted
        for id, pr in self.prs.iter_mut():
            pr.reset(last_index + 1)
            if id == self.id:
                pr.matched = persisted
                pr.committed_index = committed

    def append_entry(self, es: List[Entry]) -> bool:
        """Leader-side append; stamps term/index
        (reference: raft.rs:977-991)."""
        if not self.maybe_increase_uncommitted_size(es):
            return False
        li = self.raft_log.last_index()
        for i, e in enumerate(es):
            e.term = self.term
            e.index = li + 1 + i
        self.raft_log.append(es)
        # self's pr.matched is NOT updated until on_persist_entries.
        return True

    def on_persist_entries(self, index: int, term: int) -> None:
        """Async-persistence notification (reference: raft.rs:994-1016)."""
        update = self.raft_log.maybe_persist(index, term)
        if update and self.state == StateRole.Leader:
            if term != self.term:
                logger.error(
                    "leader's persisted index changed but term %s != %s",
                    term,
                    self.term,
                )
            pr = self.prs.get_mut(self.id)
            if (
                pr is not None
                and pr.maybe_update(index)
                and self.maybe_commit()
                and self.should_bcast_commit()
            ):
                self.bcast_append()

    def on_persist_snap(self, index: int) -> None:
        """reference: raft.rs:1019-1021"""
        self.raft_log.maybe_persist_snap(index)

    # --- tick (reference: raft.rs:1024-1079): THE MultiRaft hot loop ---

    def tick(self) -> bool:
        """Advance the logical clock by one tick; True if there is probably
        new readiness (reference: raft.rs:1024-1031)."""
        if self.state == StateRole.Leader:
            return self.tick_heartbeat()
        return self.tick_election()

    def tick_election(self) -> bool:
        """reference: raft.rs:1037-1047"""
        self.election_elapsed += 1
        if not self.pass_election_timeout() or not self.promotable:
            return False
        self.election_elapsed = 0
        m = new_message(INVALID_ID, MessageType.MsgHup, self.id)
        try:
            self.step(m)
        except RaftError:
            pass
        return True

    def tick_heartbeat(self) -> bool:
        """reference: raft.rs:1051-1079"""
        self.heartbeat_elapsed += 1
        self.election_elapsed += 1

        has_ready = False
        if self.election_elapsed >= self.election_timeout:
            self.election_elapsed = 0
            if self.check_quorum:
                has_ready = True
                m = new_message(INVALID_ID, MessageType.MsgCheckQuorum, self.id)
                try:
                    self.step(m)
                except RaftError:
                    pass
            if self.state == StateRole.Leader and self.lead_transferee is not None:
                self.abort_leader_transfer()

        if self.state != StateRole.Leader:
            return has_ready

        if self.heartbeat_elapsed >= self.heartbeat_timeout:
            self.heartbeat_elapsed = 0
            has_ready = True
            m = new_message(INVALID_ID, MessageType.MsgBeat, self.id)
            try:
                self.step(m)
            except RaftError:
                pass
        return has_ready

    # --- role transitions (reference: raft.rs:1082-1202) ---

    def become_follower(self, term: int, leader_id: int) -> None:
        """reference: raft.rs:1082-1093"""
        pending_request_snapshot = self.pending_request_snapshot
        self.reset(term)
        self.leader_id = leader_id
        self.state = StateRole.Follower
        self.pending_request_snapshot = pending_request_snapshot
        if self.metrics is not None:
            self.metrics.on_transition(
                self.state, self._group, self.id, self.term
            )

    def become_candidate(self) -> None:
        """reference: raft.rs:1101-1117"""
        assert self.state != StateRole.Leader, (
            "invalid transition [leader -> candidate]"
        )
        self.reset(self.term + 1)
        self.vote = self.id
        self.state = StateRole.Candidate
        if self.metrics is not None:
            self.metrics.on_transition(
                self.state, self._group, self.id, self.term
            )

    def become_pre_candidate(self) -> None:
        """Pre-candidate changes only the role: term/vote stay untouched
        (reference: raft.rs:1124-1143)."""
        assert self.state != StateRole.Leader, (
            "invalid transition [leader -> pre-candidate]"
        )
        self.state = StateRole.PreCandidate
        self.prs.reset_votes()
        self.leader_id = INVALID_ID
        if self.metrics is not None:
            self.metrics.on_transition(
                self.state, self._group, self.id, self.term
            )

    def become_leader(self) -> None:
        """reference: raft.rs:1151-1202"""
        assert self.state != StateRole.Follower, (
            "invalid transition [follower -> leader]"
        )
        self.reset(self.term)
        self.leader_id = self.id
        self.state = StateRole.Leader
        if self.metrics is not None:
            self.metrics.on_transition(
                self.state, self._group, self.id, self.term
            )
            self.metrics.on_election_won(self._group, self.id, self.term)

        last_index = self.raft_log.last_index()
        # Logs can't change while (pre)candidate and must be persisted before
        # RequestVote is sent, so last == persisted here.
        assert last_index == self.raft_log.persisted

        self.uncommitted_state.uncommitted_size = 0
        self.uncommitted_state.last_log_tail_index = last_index

        self.prs.get_mut(self.id).become_replicate()

        # Conservative: any pending conf change is at or before last_index.
        self.pending_conf_index = last_index

        if not self.append_entry([Entry()]):
            raise AssertionError("appending an empty entry should never be dropped")

    def _num_pending_conf(self, ents: Sequence[Entry]) -> int:
        """reference: raft.rs:1204-1211"""
        return sum(
            1
            for e in ents
            if e.entry_type
            in (EntryType.EntryConfChange, EntryType.EntryConfChangeV2)
        )

    _CAMPAIGN_KINDS = {
        CAMPAIGN_PRE_ELECTION: "PreElection",
        CAMPAIGN_ELECTION: "Election",
        CAMPAIGN_TRANSFER: "Transfer",
    }

    def campaign(self, campaign_type: bytes) -> None:
        """Start an election round (reference: raft.rs:1217-1263)."""
        if self.metrics is not None:
            self.metrics.on_campaign(
                self._CAMPAIGN_KINDS[campaign_type],
                self._group,
                self.id,
                self.term,
            )
        if campaign_type == CAMPAIGN_PRE_ELECTION:
            self.become_pre_candidate()
            vote_msg = MessageType.MsgRequestPreVote
            term = self.term + 1  # pre-vote for the NEXT term
        else:
            self.become_candidate()
            vote_msg = MessageType.MsgRequestVote
            term = self.term

        if VoteResult.Won == self.poll(self.id, vote_msg, True):
            # Single-node cluster: we won by voting for ourselves.
            return

        commit, commit_term = self.raft_log.commit_info()
        for id in sorted(self.prs.conf.voters.ids()):
            if id == self.id:
                continue
            m = new_message(id, vote_msg, None)
            m.term = term
            m.index = self.raft_log.last_index()
            m.log_term = self.raft_log.last_term()
            m.commit = commit
            m.commit_term = commit_term
            if campaign_type == CAMPAIGN_TRANSFER:
                m.context = campaign_type
            self.send(m)

    # --- the step function (reference: raft.rs:1280-1470) ---

    def step(self, m: Message) -> None:
        """Advance the state machine with one inbound message."""
        if self.metrics is not None:
            self.metrics.on_recv(m.msg_type)
        # Term epoch handling: may step us down to follower.
        if m.term == 0:
            pass  # local message
        elif m.term > self.term:
            if m.msg_type in (
                MessageType.MsgRequestVote,
                MessageType.MsgRequestPreVote,
            ):
                force = m.context == CAMPAIGN_TRANSFER
                in_lease = (
                    self.check_quorum
                    and self.leader_id != INVALID_ID
                    and self.election_elapsed < self.election_timeout
                )
                if not force and in_lease:
                    # Within the lease of a live leader we neither bump our
                    # term nor grant the vote (joint-consensus concern #3).
                    return

            if m.msg_type == MessageType.MsgRequestPreVote or (
                m.msg_type == MessageType.MsgRequestPreVoteResponse and not m.reject
            ):
                # Pre-vote requests never bump our term; granted pre-vote
                # responses carry our own future term.
                pass
            else:
                if m.msg_type in (
                    MessageType.MsgAppend,
                    MessageType.MsgHeartbeat,
                    MessageType.MsgSnapshot,
                ):
                    self.become_follower(m.term, m.from_)
                else:
                    self.become_follower(m.term, INVALID_ID)
        elif m.term < self.term:
            if (self.check_quorum or self.pre_vote) and m.msg_type in (
                MessageType.MsgHeartbeat,
                MessageType.MsgAppend,
            ):
                # Nudge a stale leader with a response carrying our term so
                # it steps down, without disruptive term inflation.
                self.send(new_message(m.from_, MessageType.MsgAppendResponse, None))
            elif m.msg_type == MessageType.MsgRequestPreVote:
                # Reject explicitly to avoid pre-vote deadlock after upgrade.
                to_send = new_message(
                    m.from_, MessageType.MsgRequestPreVoteResponse, None
                )
                to_send.term = self.term
                to_send.reject = True
                self.send(to_send)
            # other lower-term messages are ignored
            return

        self.before_step_hook(m)

        if m.msg_type == MessageType.MsgHup:
            self.hup(False)
        elif m.msg_type in (
            MessageType.MsgRequestVote,
            MessageType.MsgRequestPreVote,
        ):
            # We can vote if it repeats a vote we already cast, we haven't
            # voted and see no leader this term, or it's a future-term
            # PreVote...
            can_vote = (
                (self.vote == m.from_)
                or (self.vote == INVALID_ID and self.leader_id == INVALID_ID)
                or (
                    m.msg_type == MessageType.MsgRequestPreVote
                    and m.term > self.term
                )
            )
            # ...and the candidate's log is up to date, with priority gating.
            if (
                can_vote
                and self.raft_log.is_up_to_date(m.index, m.log_term)
                and (
                    m.index > self.raft_log.last_index()
                    or self.priority <= m.priority
                )
            ):
                # Respond with the MESSAGE's term (differs from ours for
                # pre-votes from partitioned-away nodes).
                to_send = new_message(m.from_, vote_resp_msg_type(m.msg_type), None)
                to_send.reject = False
                to_send.term = m.term
                self.send(to_send)
                if self.metrics is not None:
                    self.metrics.on_vote_grant(
                        m.msg_type == MessageType.MsgRequestPreVote,
                        self._group,
                        self.id,
                        self.term,
                        m.from_,
                    )
                if m.msg_type == MessageType.MsgRequestVote:
                    # Only real votes are recorded.
                    self.election_elapsed = 0
                    self.vote = m.from_
            else:
                to_send = new_message(m.from_, vote_resp_msg_type(m.msg_type), None)
                to_send.reject = True
                to_send.term = self.term
                commit, commit_term = self.raft_log.commit_info()
                to_send.commit = commit
                to_send.commit_term = commit_term
                self.send(to_send)
                self.maybe_commit_by_vote(m)
        else:
            if self.state in (StateRole.PreCandidate, StateRole.Candidate):
                self.step_candidate(m)
            elif self.state == StateRole.Follower:
                self.step_follower(m)
            else:
                self.step_leader(m)

    def before_step_hook(self, m: Message) -> None:
        """Fault-injection hook at the reference's `before_step` failpoint
        site (reference: raft.rs:1413-1414); tests monkeypatch this."""

    def hup(self, transfer_leader: bool) -> None:
        """reference: raft.rs:1472-1525"""
        if self.state == StateRole.Leader:
            return

        # A pending snapshot has already applied its configuration, so
        # campaigning is safe as long as no conf change is pending in entries.
        first_index = self.raft_log.unstable.maybe_first_index()
        if first_index is None:
            first_index = self.raft_log.applied + 1

        ents = self.raft_log.slice(first_index, self.raft_log.committed + 1, None)
        if self._num_pending_conf(ents) != 0:
            return
        if transfer_leader:
            self.campaign(CAMPAIGN_TRANSFER)
        elif self.pre_vote:
            self.campaign(CAMPAIGN_PRE_ELECTION)
        else:
            self.campaign(CAMPAIGN_ELECTION)

    # --- leader handlers (reference: raft.rs:1559-2123) ---

    def handle_append_response(self, m: Message) -> None:
        """reference: raft.rs:1559-1775 (incl. the fast-rejection probing
        described in the long comment there: probe at most once per term in
        the leader's log instead of once per index)."""
        next_probe_index = m.reject_hint
        if m.reject and m.log_term > 0:
            next_probe_index = self.raft_log.find_conflict_by_term(
                m.reject_hint, m.log_term
            )[0]

        pr = self.prs.get_mut(m.from_)
        if pr is None:
            return
        pr.recent_active = True
        pr.update_committed(m.commit)

        if m.reject:
            if pr.maybe_decr_to(m.index, next_probe_index, m.request_snapshot):
                if pr.state == ProgressState.Replicate:
                    pr.become_probe()
                self.send_append(m.from_)
            return

        old_paused = pr.is_paused()
        if not pr.maybe_update(m.index):
            return

        if pr.state == ProgressState.Probe:
            pr.become_replicate()
        elif pr.state == ProgressState.Snapshot:
            if pr.maybe_snapshot_abort():
                pr.become_probe()
        elif pr.state == ProgressState.Replicate:
            pr.ins.free_to(m.index)

        if self.maybe_commit():
            if self.should_bcast_commit():
                self.bcast_append()
        elif old_paused:
            self.send_append(m.from_)

        # Flow control may allow several size-limited sends now.
        pr = self.prs.get_mut(m.from_)
        while self._maybe_send_append(m.from_, pr, allow_empty=False):
            pass

        if m.from_ == self.lead_transferee:
            if pr.matched == self.raft_log.last_index():
                self.send_timeout_now(m.from_)

    def handle_heartbeat_response(self, m: Message) -> None:
        """reference: raft.rs:1777-1819"""
        pr = self.prs.get_mut(m.from_)
        if pr is None:
            return
        pr.update_committed(m.commit)
        pr.recent_active = True
        pr.resume()

        # Free one inflight slot so a full window can make progress.
        if pr.state == ProgressState.Replicate and pr.ins.full():
            pr.ins.free_first_one()
        if (
            pr.matched < self.raft_log.last_index()
            or pr.pending_request_snapshot != INVALID_INDEX
        ):
            self._maybe_send_append(m.from_, pr, allow_empty=True)

        if self.read_only.option != ReadOnlyOption.Safe or not m.context:
            return

        acks = self.read_only.recv_ack(m.from_, m.context)
        if acks is None or not self.prs.has_quorum(acks):
            return

        for rs in self.read_only.advance(m.context):
            resp = self.handle_ready_read_index(rs.req, rs.index)
            if resp is not None:
                self.send(resp)

    def handle_transfer_leader(self, m: Message) -> None:
        """reference: raft.rs:1821-1889"""
        if self.prs.get(m.from_) is None:
            return
        from_ = m.from_
        if from_ in self.prs.conf.learners:
            return
        lead_transferee = from_
        if self.lead_transferee is not None:
            if self.lead_transferee == lead_transferee:
                return
            self.abort_leader_transfer()
        if lead_transferee == self.id:
            return
        # Transfer should finish within one election timeout.
        self.election_elapsed = 0
        self.lead_transferee = lead_transferee
        pr = self.prs.get_mut(from_)
        if pr.matched == self.raft_log.last_index():
            self.send_timeout_now(lead_transferee)
        else:
            self._maybe_send_append(lead_transferee, pr, allow_empty=True)

    def handle_snapshot_status(self, m: Message) -> None:
        """reference: raft.rs:1891-1929"""
        pr = self.prs.get_mut(m.from_)
        if pr is None:
            return
        if pr.state != ProgressState.Snapshot:
            return
        if m.reject:
            pr.snapshot_failure()
            pr.become_probe()
        else:
            pr.become_probe()
        # Snapshot done: wait for MsgAppendResponse before the next append;
        # failed: wait out a heartbeat interval.
        pr.pause()
        pr.pending_request_snapshot = INVALID_INDEX

    def handle_unreachable(self, m: Message) -> None:
        """reference: raft.rs:1931-1954"""
        pr = self.prs.get_mut(m.from_)
        if pr is None:
            return
        # An optimistic MsgAppend was probably lost.
        if pr.state == ProgressState.Replicate:
            pr.become_probe()

    def step_leader(self, m: Message) -> None:
        """reference: raft.rs:1956-2123"""
        # Messages that need no per-peer progress:
        if m.msg_type == MessageType.MsgBeat:
            if self.metrics is not None:
                self.metrics.on_beat()
            self.bcast_heartbeat()
            return
        if m.msg_type == MessageType.MsgCheckQuorum:
            if not self.check_quorum_active():
                self.become_follower(self.term, INVALID_ID)
            return
        if m.msg_type == MessageType.MsgPropose:
            if not m.entries:
                raise AssertionError("stepped empty MsgProp")
            if self.id not in self.prs.progress:
                # We were removed from the config while leading.
                raise ProposalDropped()
            if self.lead_transferee is not None:
                raise ProposalDropped()

            for i, e in enumerate(m.entries):
                if e.entry_type == EntryType.EntryConfChange:
                    try:
                        cc = decode_conf_change(e.data).into_v2()
                    except ValueError:
                        raise ProposalDropped()
                elif e.entry_type == EntryType.EntryConfChangeV2:
                    try:
                        cc = decode_conf_change_v2(e.data)
                    except ValueError:
                        raise ProposalDropped()
                else:
                    continue

                if self.has_pending_conf():
                    reason = "possible unapplied conf change"
                else:
                    already_joint = conf_is_joint(self.prs.conf)
                    want_leave = not cc.changes
                    if already_joint and not want_leave:
                        reason = "must transition out of joint config first"
                    elif not already_joint and want_leave:
                        reason = "not in joint state; refusing empty conf change"
                    else:
                        reason = ""

                if not reason:
                    self.pending_conf_index = self.raft_log.last_index() + i + 1
                else:
                    # Elide the conf change, keeping log positions stable.
                    m.entries[i] = Entry(entry_type=EntryType.EntryNormal)

            if not self.append_entry(m.entries):
                raise ProposalDropped()  # uncommitted-size limit reached
            self.bcast_append()
            return
        if m.msg_type == MessageType.MsgReadIndex:
            if not self.commit_to_current_term():
                # No entry committed in our term yet: reject read requests.
                return
            if self.prs.is_singleton():
                resp = self.handle_ready_read_index(m, self.raft_log.committed)
                if resp is not None:
                    self.send(resp)
                return
            if self.read_only.option == ReadOnlyOption.Safe:
                ctx = bytes(m.entries[0].data)
                self.read_only.add_request(self.raft_log.committed, m, self.id)
                self._bcast_heartbeat_with_ctx(ctx)
            else:  # LeaseBased
                resp = self.handle_ready_read_index(m, self.raft_log.committed)
                if resp is not None:
                    self.send(resp)
            return

        if m.msg_type == MessageType.MsgAppendResponse:
            self.handle_append_response(m)
        elif m.msg_type == MessageType.MsgHeartbeatResponse:
            self.handle_heartbeat_response(m)
        elif m.msg_type == MessageType.MsgSnapStatus:
            self.handle_snapshot_status(m)
        elif m.msg_type == MessageType.MsgUnreachable:
            self.handle_unreachable(m)
        elif m.msg_type == MessageType.MsgTransferLeader:
            self.handle_transfer_leader(m)

    def maybe_commit_by_vote(self, m: Message) -> None:
        """Fast-forward commit from a vote message's commit info
        (reference: raft.rs:2126-2164)."""
        if m.commit == 0 or m.commit_term == 0:
            return
        last_commit = self.raft_log.committed
        if m.commit <= last_commit or self.state == StateRole.Leader:
            return
        if not self.raft_log.maybe_commit(m.commit, m.commit_term):
            return

        if self.state not in (StateRole.Candidate, StateRole.PreCandidate):
            return
        ents = self.raft_log.slice(
            last_commit + 1, self.raft_log.committed + 1, None
        )
        if self._num_pending_conf(ents) != 0:
            # Conservatively step down: the quorum may be changing.
            self.become_follower(self.term, INVALID_ID)

    def poll(self, from_: int, t: MessageType, vote: bool) -> VoteResult:
        """reference: raft.rs:2166-2201"""
        self.prs.record_vote(from_, vote)
        _, _, res = self.prs.tally_votes()
        if res == VoteResult.Won:
            if self.state == StateRole.PreCandidate:
                self.campaign(CAMPAIGN_ELECTION)
            else:
                self.become_leader()
                self.bcast_append()
        elif res == VoteResult.Lost:
            self.become_follower(self.term, INVALID_ID)
        return res

    def step_candidate(self, m: Message) -> None:
        """Shared by Candidate and PreCandidate
        (reference: raft.rs:2205-2255)."""
        if m.msg_type == MessageType.MsgPropose:
            raise ProposalDropped()
        elif m.msg_type == MessageType.MsgAppend:
            self.become_follower(m.term, m.from_)
            self.handle_append_entries(m)
        elif m.msg_type == MessageType.MsgHeartbeat:
            self.become_follower(m.term, m.from_)
            self.handle_heartbeat(m)
        elif m.msg_type == MessageType.MsgSnapshot:
            self.become_follower(m.term, m.from_)
            self.handle_snapshot(m)
        elif m.msg_type in (
            MessageType.MsgRequestPreVoteResponse,
            MessageType.MsgRequestVoteResponse,
        ):
            # Ignore stale pre-vote responses while a real candidate et al.
            if (
                self.state == StateRole.PreCandidate
                and m.msg_type != MessageType.MsgRequestPreVoteResponse
            ) or (
                self.state == StateRole.Candidate
                and m.msg_type != MessageType.MsgRequestVoteResponse
            ):
                return
            self.poll(m.from_, m.msg_type, not m.reject)
            self.maybe_commit_by_vote(m)
        elif m.msg_type == MessageType.MsgTimeoutNow:
            pass  # candidates ignore TimeoutNow

    def step_follower(self, m: Message) -> None:
        """reference: raft.rs:2257-2354"""
        if m.msg_type == MessageType.MsgPropose:
            if self.leader_id == INVALID_ID:
                raise ProposalDropped()
            m.to = self.leader_id
            self.send(m)
        elif m.msg_type == MessageType.MsgAppend:
            self.election_elapsed = 0
            self.leader_id = m.from_
            self.handle_append_entries(m)
        elif m.msg_type == MessageType.MsgHeartbeat:
            self.election_elapsed = 0
            self.leader_id = m.from_
            self.handle_heartbeat(m)
        elif m.msg_type == MessageType.MsgSnapshot:
            self.election_elapsed = 0
            self.leader_id = m.from_
            self.handle_snapshot(m)
        elif m.msg_type == MessageType.MsgTransferLeader:
            if self.leader_id == INVALID_ID:
                return
            m.to = self.leader_id
            self.send(m)
        elif m.msg_type == MessageType.MsgTimeoutNow:
            if self.promotable:
                # Transfers skip pre-vote: we know we're not partitioned.
                self.hup(True)
        elif m.msg_type == MessageType.MsgReadIndex:
            if self.leader_id == INVALID_ID:
                return
            m.to = self.leader_id
            self.send(m)
        elif m.msg_type == MessageType.MsgReadIndexResp:
            if len(m.entries) != 1:
                return
            self.read_states.append(
                ReadState(index=m.index, request_ctx=bytes(m.entries[0].data))
            )
            # index/term are the leader's commit index + current term.
            self.raft_log.maybe_commit(m.index, m.term)

    def request_snapshot(self, request_index: int) -> None:
        """Follower-initiated snapshot request (reference: raft.rs:2357-2385)."""
        if (
            self.state != StateRole.Leader
            and self.leader_id != INVALID_ID
            and self.snap() is None
            and self.pending_request_snapshot == INVALID_INDEX
        ):
            self.pending_request_snapshot = request_index
            self.send_request_snapshot()
            return
        raise RequestSnapshotDropped()

    def handle_append_entries(self, m: Message) -> None:
        """reference: raft.rs:2389-2448"""
        if self.pending_request_snapshot != INVALID_INDEX:
            self.send_request_snapshot()
            return
        if m.index < self.raft_log.committed:
            to_send = Message(
                msg_type=MessageType.MsgAppendResponse,
                to=m.from_,
                index=self.raft_log.committed,
                commit=self.raft_log.committed,
            )
            self.send(to_send)
            return

        to_send = Message(msg_type=MessageType.MsgAppendResponse, to=m.from_)
        res = self.raft_log.maybe_append(m.index, m.log_term, m.commit, m.entries)
        if res is not None:
            to_send.index = res[1]
        else:
            # Reject with a fast-probe hint: the largest index whose term is
            # <= the probe's log_term (see the long analysis in the
            # reference's handle_append_response comment).
            hint_index = min(m.index, self.raft_log.last_index())
            hint_index, hint_term = self.raft_log.find_conflict_by_term(
                hint_index, m.log_term
            )
            if hint_term is None:
                raise AssertionError(f"term({hint_index}) must be valid")
            to_send.index = m.index
            to_send.reject = True
            to_send.reject_hint = hint_index
            to_send.log_term = hint_term
            if self.metrics is not None:
                self.metrics.on_append_rejected(
                    self._group, self.id, self.term, m.index
                )
        to_send.commit = self.raft_log.committed
        self.send(to_send)

    def handle_heartbeat(self, m: Message) -> None:
        """reference: raft.rs:2452-2464"""
        self.raft_log.commit_to(m.commit)
        if self.pending_request_snapshot != INVALID_INDEX:
            self.send_request_snapshot()
            return
        to_send = Message(
            msg_type=MessageType.MsgHeartbeatResponse,
            to=m.from_,
            context=m.context,
            commit=self.raft_log.committed,
        )
        self.send(to_send)

    def handle_snapshot(self, m: Message) -> None:
        """reference: raft.rs:2466-2497"""
        snapshot = m.get_snapshot()
        if self.restore(snapshot):
            to_send = Message(
                msg_type=MessageType.MsgAppendResponse,
                to=m.from_,
                index=self.raft_log.last_index(),
            )
        else:
            to_send = Message(
                msg_type=MessageType.MsgAppendResponse,
                to=m.from_,
                index=self.raft_log.committed,
            )
        self.send(to_send)

    def restore(self, snap: Snapshot) -> bool:
        """Restore log + configuration from a snapshot
        (reference: raft.rs:2501-2600)."""
        meta = snap.metadata
        if meta.index < self.raft_log.committed:
            return False
        if self.state != StateRole.Follower:
            # Defense in depth: should be unreachable.
            self.become_follower(self.term + 1, INVALID_ID)
            return False

        # Throw away snapshots that don't include us in the config.
        cs = meta.conf_state
        if self.id not in set(cs.voters) | set(cs.learners) | set(
            cs.voters_outgoing
        ):
            # (learners_next ⊆ voters_outgoing, no need to check it)
            return False

        if self.pending_request_snapshot == INVALID_INDEX and self.raft_log.match_term(
            meta.index, meta.term
        ):
            # Fast path: our log already covers the snapshot.
            self.raft_log.commit_to(meta.index)
            return False

        self.raft_log.restore(snap)
        cs = self.raft_log.pending_snapshot().metadata.conf_state

        self.prs.clear()
        confchange_restore(self.prs, self.raft_log.last_index(), cs)
        new_cs = self.post_conf_change()
        if not conf_state_eq(cs, new_cs):
            raise AssertionError(f"invalid restore: {cs} != {new_cs}")

        pr = self.prs.get_mut(self.id)
        pr.maybe_update(pr.next_idx - 1)
        self.pending_request_snapshot = INVALID_INDEX
        return True

    def post_conf_change(self) -> ConfState:
        """React to an installed configuration (reference: raft.rs:2604-2673)."""
        cs = self.prs.conf.to_conf_state()
        is_voter = self.prs.conf.voters.contains(self.id)
        self.promotable = is_voter
        if not is_voter and self.state == StateRole.Leader:
            # Leader removed/demoted — defense-in-depth early return.
            return cs

        if self.state != StateRole.Leader or not cs.voters:
            return cs

        if self.maybe_commit():
            # Quorum shrank: more entries may be committed now.
            self.bcast_append()
        else:
            # Probe newly added replicas immediately.
            for id, pr in self.prs.iter_mut():
                if id == self.id:
                    continue
                self._maybe_send_append(id, pr, allow_empty=False)

        # Smaller quorum may also satisfy pending reads.
        ctx = self.read_only.last_pending_request_ctx()
        if ctx is not None:
            acks = self.read_only.recv_ack(self.id, ctx)
            if acks is not None and self.prs.has_quorum(acks):
                for rs in self.read_only.advance(ctx):
                    resp = self.handle_ready_read_index(rs.req, rs.index)
                    if resp is not None:
                        self.send(resp)

        if self.lead_transferee is not None and not self.prs.conf.voters.contains(
            self.lead_transferee
        ):
            self.abort_leader_transfer()
        return cs

    def has_pending_conf(self) -> bool:
        """reference: raft.rs:2679-2681 (may be false-positive)"""
        return self.pending_conf_index > self.raft_log.applied

    def should_bcast_commit(self) -> bool:
        """reference: raft.rs:2684-2686"""
        return not self.skip_bcast_commit or self.has_pending_conf()

    def apply_conf_change(self, cc: ConfChangeV2) -> ConfState:
        """Apply a committed conf change to the tracker
        (reference: raft.rs:2695-2707)."""
        changer = Changer(self.prs)
        if cc.leave_joint():
            cfg, changes = changer.leave_joint()
        else:
            auto_leave = cc.enter_joint()
            if auto_leave is not None:
                cfg, changes = changer.enter_joint(auto_leave, cc.changes)
            else:
                cfg, changes = changer.simple(cc.changes)
        self.prs.apply_conf(cfg, changes, self.raft_log.last_index())
        if self.metrics is not None:
            self.metrics.on_conf_change(self._group, self.id, self.term)
        return self.post_conf_change()

    def load_state(self, hs: HardState) -> None:
        """reference: raft.rs:2721-2734"""
        if hs.commit < self.raft_log.committed or hs.commit > self.raft_log.last_index():
            raise AssertionError(
                f"hs.commit {hs.commit} is out of range "
                f"[{self.raft_log.committed}, {self.raft_log.last_index()}]"
            )
        self.raft_log.committed = hs.commit
        self.term = hs.term
        self.vote = hs.vote

    def pass_election_timeout(self) -> bool:
        """reference: raft.rs:2739-2741"""
        return self.election_elapsed >= self.randomized_election_timeout

    def reset_randomized_election_timeout(self) -> None:
        """Counter-based deterministic replacement for the reference's
        thread_rng (reference: raft.rs:2744-2756): both the scalar and the
        TPU backends derive the timeout from (node_key, term) with the same
        32-bit mixer, so they draw identical values."""
        self.randomized_election_timeout = deterministic_timeout(
            self._timeout_key,
            self.term,
            self.min_election_timeout,
            self.max_election_timeout,
        )

    def check_quorum_active(self) -> bool:
        """reference: raft.rs:2763-2766"""
        return self.prs.quorum_recently_active(self.id)

    def send_timeout_now(self, to: int) -> None:
        """reference: raft.rs:2769-2772"""
        self.send(new_message(to, MessageType.MsgTimeoutNow, None))

    def abort_leader_transfer(self) -> None:
        self.lead_transferee = None

    def send_request_snapshot(self) -> None:
        """reference: raft.rs:2779-2788"""
        m = Message(
            msg_type=MessageType.MsgAppendResponse,
            index=self.raft_log.committed,
            reject=True,
            reject_hint=self.raft_log.last_index(),
            to=self.leader_id,
            request_snapshot=self.pending_request_snapshot,
        )
        self.send(m)

    def handle_ready_read_index(self, req: Message, index: int) -> Optional[Message]:
        """reference: raft.rs:2790-2805"""
        if req.from_ == INVALID_ID or req.from_ == self.id:
            self.read_states.append(
                ReadState(index=index, request_ctx=bytes(req.entries[0].data))
            )
            return None
        return Message(
            msg_type=MessageType.MsgReadIndexResp,
            to=req.from_,
            index=index,
            entries=req.entries,
        )

    def reduce_uncommitted_size(self, ents: Sequence[Entry]) -> None:
        """reference: raft.rs:2808-2823"""
        if self.state != StateRole.Leader:
            return
        self.uncommitted_state.maybe_reduce_uncommitted_size(ents)

    def maybe_increase_uncommitted_size(self, ents: Sequence[Entry]) -> bool:
        return self.uncommitted_state.maybe_increase_uncommitted_size(ents)

    def uncommitted_size(self) -> int:
        return self.uncommitted_state.uncommitted_size
