"""Unified metrics + structured tracing for raft-tpu (SURVEY.md §5.1: the
reference's observability is structured slog tracing + Criterion; this module
is our equivalent counter plane for the scalar core, the MultiRaft driver,
and — via the device counter plane in `raft_tpu.multiraft.kernels` — the
batched sim).

Zero dependencies beyond the standard library.  Three pieces:

* `Registry` — counters / gauges / histograms with optional labels and
  Prometheus text exposition (`expose()`); `snapshot()` returns a flat dict
  for programmatic scraping (`MultiRaft.metrics_snapshot()`).
* `EventTracer` — JSONL structured event tracing.  Every event is one JSON
  object per line with a monotonic `seq`, an `event` name, and arbitrary
  tags (group, id, term, ...).  The sink is a file path, a file-like object,
  or a plain list (tests).
* `Metrics` — the facade the consensus core is instrumented against.  An
  instance is attached to `Config.metrics`; every hot-path hook in
  `raft.py` / `raw_node.py` / `multiraft/driver.py` is guarded by a single
  `if self.metrics is not None` branch, so the disabled path (the default)
  costs exactly one predictable branch and no allocation.

Threading contract: sample mutation (inc/set/observe) is **single-writer**
— the scalar core and the MultiRaft driver are single-threaded, and a
per-sample lock would tax every hot-path event for a shape the library
doesn't have.  Scraping (`expose()`/`snapshot()`/`total()`) IS safe from
another thread while the writer runs: registration and labelset creation
are lock-guarded, and the scrape paths iterate point-in-time copies.

The device-side counter plane (campaigns fired, heartbeats emitted,
elections won, commit entries advanced) lives in `SimState`-adjacent arrays
summed inside the jitted step — see `raft_tpu.multiraft.sim.ClusterSim` and
the `CTR_*` indices in `raft_tpu.multiraft.kernels`.  Its parity contract
against the scalar counters here is asserted by
`tests/test_counter_parity.py`.
"""

from __future__ import annotations

import io
import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "EventTracer",
    "Metrics",
    "DEFAULT_LATENCY_BUCKETS",
]

# Default histogram bounds for host<->device latencies (seconds).
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    50e-6, 100e-6, 250e-6, 500e-6,
    1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3, 50e-3, 100e-3, 250e-3, 1.0,
)

def _role_names() -> Dict[int, str]:
    """StateRole codes -> names, imported lazily (module-load order: the
    package __init__ pulls metrics in before raft)."""
    from .raft import StateRole

    return dict(StateRole._NAMES)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(labelnames: Sequence[str], labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    pairs = ",".join(
        f'{k}="{_escape_label_value(str(v))}"'
        for k, v in zip(labelnames, labelvalues)
    )
    return "{" + pairs + "}"


class Counter:
    """Monotonically increasing counter."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value: float = 0

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError("counters can only increase")
        self.value += n


class Gauge:
    """Point-in-time value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value: float = 0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n

    def dec(self, n: float = 1) -> None:
        self.value -= n


class Histogram:
    """Fixed-bound histogram (cumulative buckets at exposition time)."""

    __slots__ = ("bounds", "bucket_counts", "sum", "count")

    def __init__(self, bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        b = sorted(bounds)
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds: Tuple[float, ...] = tuple(b)
        # One slot per finite bound plus the +Inf overflow slot.
        self.bucket_counts: List[int] = [0] * (len(b) + 1)
        self.sum: float = 0.0
        self.count: int = 0

    def observe(self, v: float) -> None:
        self.sum += v
        self.count += 1
        for i, bound in enumerate(self.bounds):
            if v <= bound:
                self.bucket_counts[i] += 1
                return
        self.bucket_counts[-1] += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le_bound, cumulative_count), ...] ending with (+inf, count)."""
        out: List[Tuple[float, int]] = []
        running = 0
        for bound, c in zip(self.bounds, self.bucket_counts):
            running += c
            out.append((bound, running))
        out.append((float("inf"), self.count))
        return out


_KIND_NAMES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class _Family:
    """One metric name with a fixed label schema and per-labelset children.

    With no labels the family proxies inc/set/observe straight to its single
    implicit child, so call sites read `fam.inc()` either way.
    """

    def __init__(
        self,
        name: str,
        help: str,
        kind: type,
        labelnames: Sequence[str],
        histogram_bounds: Optional[Sequence[float]] = None,
    ):
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        self._bounds = histogram_bounds
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _new_child(self):
        if self.kind is Histogram:
            return Histogram(self._bounds or DEFAULT_LATENCY_BUCKETS)
        return self.kind()

    def labels(self, *labelvalues, **labelkv):
        if labelkv:
            if labelvalues:
                raise ValueError("pass label values positionally OR by name")
            try:
                labelvalues = tuple(str(labelkv[k]) for k in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e} (schema {self.labelnames})"
                ) from None
        else:
            labelvalues = tuple(str(v) for v in labelvalues)
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {labelvalues}"
            )
        child = self._children.get(labelvalues)
        if child is None:
            # Double-checked creation: one Metrics instance is shared across
            # every node of a deployment, so two threads can first-touch the
            # same labelset concurrently; without the lock one child would
            # silently shadow the other and its increments would vanish.
            with self._lock:
                child = self._children.get(labelvalues)
                if child is None:
                    child = self._new_child()
                    self._children[labelvalues] = child
        return child

    # --- no-label conveniences ---

    def _solo(self):
        return self.labels()

    def inc(self, n: float = 1) -> None:
        self._solo().inc(n)

    def set(self, v: float) -> None:
        self._solo().set(v)

    def observe(self, v: float) -> None:
        self._solo().observe(v)

    @property
    def value(self) -> float:
        return self._solo().value

    def total(self) -> float:
        """Sum over all label children (counters/gauges)."""
        return sum(c.value for c in list(self._children.values()))


class Registry:
    """Named metric families; thread-safe registration, idempotent by name."""

    def __init__(self) -> None:
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _get_or_create(
        self,
        name: str,
        help: str,
        kind: type,
        labelnames: Sequence[str],
        histogram_bounds: Optional[Sequence[float]] = None,
    ) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind is not kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"kind/label schema"
                    )
                return fam
            fam = _Family(name, help, kind, labelnames, histogram_bounds)
            self._families[name] = fam
            return fam

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._get_or_create(name, help, Counter, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._get_or_create(name, help, Gauge, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> _Family:
        return self._get_or_create(name, help, Histogram, labelnames, buckets)

    def get(self, name: str) -> Optional[_Family]:
        return self._families.get(name)

    @staticmethod
    def _fmt_value(v: float) -> str:
        if isinstance(v, int):
            return str(v)
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(float(v))

    @staticmethod
    def _fmt_le(bound: float) -> str:
        return "+Inf" if bound == float("inf") else Registry._fmt_value(bound)

    def expose(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out = io.StringIO()
        # list() copies: a writer thread may first-touch a labelset while a
        # scrape thread iterates (see the module threading contract).
        for name, fam in list(self._families.items()):
            if fam.help:
                out.write(f"# HELP {name} {fam.help}\n")
            out.write(f"# TYPE {name} {_KIND_NAMES[fam.kind]}\n")
            for labelvalues, child in list(fam._children.items()):
                labels = _format_labels(fam.labelnames, labelvalues)
                if fam.kind is Histogram:
                    for bound, cum in child.cumulative():
                        le = _format_labels(
                            fam.labelnames + ("le",),
                            labelvalues + (self._fmt_le(bound),),
                        )
                        out.write(f"{name}_bucket{le} {cum}\n")
                    out.write(
                        f"{name}_sum{labels} {self._fmt_value(child.sum)}\n"
                    )
                    out.write(f"{name}_count{labels} {child.count}\n")
                else:
                    out.write(
                        f"{name}{labels} {self._fmt_value(child.value)}\n"
                    )
        return out.getvalue()

    def snapshot(self) -> Dict[str, float]:
        """Flat {sample_name: value} dict (histograms expose _sum/_count)."""
        out: Dict[str, float] = {}
        for name, fam in list(self._families.items()):
            for labelvalues, child in list(fam._children.items()):
                labels = _format_labels(fam.labelnames, labelvalues)
                if fam.kind is Histogram:
                    out[f"{name}_sum{labels}"] = child.sum
                    out[f"{name}_count{labels}"] = child.count
                else:
                    out[f"{name}{labels}"] = child.value
        return out


class EventTracer:
    """Structured JSONL event sink.

    sink: a file path (opened lazily, line-buffered), a file-like object
    with .write(), or a list (events appended as dicts — the test sink).
    Every event carries a monotonic `seq` so interleavings reconstruct.
    """

    def __init__(self, sink: Union[str, list, io.TextIOBase, object]):
        self._sink = sink
        self._fh = None
        self._seq = 0
        self._lock = threading.Lock()

    def emit(self, event: str, **fields) -> None:
        with self._lock:
            seq = self._seq
            self._seq += 1
            record = {"seq": seq, "ts": time.time(), "event": event}
            record.update(fields)
            if isinstance(self._sink, list):
                self._sink.append(record)
                return
            fh = self._fh
            if fh is None:
                if isinstance(self._sink, str):
                    fh = open(self._sink, "a", buffering=1)
                else:
                    fh = self._sink
                self._fh = fh
            fh.write(json.dumps(record, separators=(",", ":")) + "\n")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and isinstance(self._sink, str):
                self._fh.close()
            self._fh = None


class Metrics:
    """The instrumentation facade attached to `Config.metrics`.

    One instance is shared by every node of a deployment (the MultiRaft
    driver's per-group Config copies all carry the same reference), so the
    registry aggregates across groups while traces stay per-group tagged.
    All handles are pre-bound at construction: the per-event cost is one
    list index + one float add.
    """

    def __init__(
        self,
        registry: Optional[Registry] = None,
        tracer: Optional[EventTracer] = None,
    ):
        from .eraftpb import MessageType  # local import: keep module light

        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer
        self._role_names = _role_names()
        r = self.registry

        sent = r.counter(
            "raft_msgs_sent_total", "Messages queued for send", ("type",)
        )
        recv = r.counter(
            "raft_msgs_received_total", "Messages stepped", ("type",)
        )
        # Index by int(MessageType) — values are contiguous 0..18.
        self._sent_by_type = [sent.labels(type=t.name) for t in MessageType]
        self._recv_by_type = [recv.labels(type=t.name) for t in MessageType]

        trans = r.counter(
            "raft_state_transitions_total", "Role transitions", ("to",)
        )
        self._trans_by_role = [
            trans.labels(to=self._role_names[i])
            for i in sorted(self._role_names)
        ]
        self.campaigns = r.counter(
            "raft_campaigns_total", "Campaigns started", ("type",)
        )
        self.votes_granted = r.counter(
            "raft_votes_granted_total", "Votes granted", ("type",)
        )
        self.elections_won = r.counter(
            "raft_elections_won_total", "become_leader transitions"
        )
        self.beats = r.counter(
            "raft_beats_total", "MsgBeat heartbeats fired at leaders"
        )
        self.commit_advances = r.counter(
            "raft_commit_advances_total", "Commit-index advance events"
        )
        self.commit_entries = r.counter(
            "raft_commit_entries_total", "Total entries newly committed"
        )
        self.appends_rejected = r.counter(
            "raft_appends_rejected_total", "MsgAppend probes rejected"
        )
        self.snapshots_sent = r.counter(
            "raft_snapshots_sent_total", "Snapshots prepared for send"
        )
        self.conf_changes = r.counter(
            "raft_conf_changes_total", "Conf changes applied"
        )
        self.ready_cycles = r.counter(
            "raft_ready_total", "Ready structs harvested"
        )
        self.advance_cycles = r.counter(
            "raft_advance_total", "Ready structs advanced"
        )
        self.must_sync = r.counter(
            "raft_must_sync_total", "Readys requiring synchronous persistence"
        )

        # MultiRaft driver plane.
        self.driver_ticks = r.counter(
            "multiraft_ticks_total", "Batched driver ticks"
        )
        self.driver_active_groups = r.counter(
            "multiraft_active_groups_total",
            "Groups whose tick fired a host-side event",
        )
        self.driver_campaigns_fired = r.counter(
            "multiraft_campaign_events_total",
            "Per-tick campaign mask population",
        )
        self.driver_beats_fired = r.counter(
            "multiraft_heartbeat_events_total",
            "Per-tick heartbeat mask population",
        )
        self.driver_checkq_fired = r.counter(
            "multiraft_check_quorum_events_total",
            "Per-tick leader election-timeout boundary mask population",
        )
        self.driver_last_active = r.gauge(
            "multiraft_last_tick_active_groups",
            "Active-group mask population of the most recent tick",
        )
        self.driver_sync_seconds = r.histogram(
            "multiraft_tick_sync_seconds",
            "Host<->device round-trip latency of the batched tick",
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        self.ready_scan_scanned = r.counter(
            "multiraft_ready_scan_groups_scanned_total",
            "Groups actually probed by ready_groups() (the dirty set)",
        )
        self.ready_scan_skipped = r.counter(
            "multiraft_ready_scan_groups_skipped_total",
            "Idle groups ready_groups() skipped without any host work",
        )

        # Fleet-health plane (multiraft/health.py HealthMonitor summaries).
        self.health_summaries = r.counter(
            "health_summaries_total", "Health summaries recorded"
        )
        self.health_leaderless = r.gauge(
            "health_groups_leaderless", "Groups currently without a leader"
        )
        self.health_stalled_leaderless = r.gauge(
            "health_groups_stalled_leaderless",
            "Groups leaderless at/over the stall threshold",
        )
        self.health_commit_stalled = r.gauge(
            "health_groups_commit_stalled",
            "Groups with a flat commit index at/over the stall threshold",
        )
        self.health_churning = r.gauge(
            "health_groups_churning",
            "Groups with term bumps in window at/over the churn threshold",
        )
        self.health_worst_score = r.gauge(
            "health_worst_group_score",
            "Worst-offender score (max of commit lag and leaderless ticks)",
        )
        # The device reduces commit lag into fixed buckets already, so this
        # is a labeled gauge family (a point-in-time distribution), not a
        # Histogram (which accumulates observations).
        self.health_commit_lag = r.gauge(
            "health_commit_lag_groups",
            "Groups per commit-lag bucket (lower bound label, ticks)",
            ("ge",),
        )
        self.health_reconfig_stalled = r.gauge(
            "health_groups_reconfig_stalled",
            "Groups sitting in a joint config with a stalled commit "
            "(HealthMonitor.record_reconfig's stall detection)",
        )

        # Autopilot plane (multiraft/autopilot.py): the closed control
        # loop's issued actions and the transfer protocol's in-flight
        # gauge.
        self.autopilot_actions = r.counter(
            "multiraft_autopilot_actions_total",
            "Autopilot heal actions issued, by kind "
            "(kicks / transfers / evacuations)",
            ("kind",),
        )
        self.health_transfer_pending = r.gauge(
            "health_groups_transfer_pending",
            "Groups with a leader transfer currently pending "
            "(lead_transferee set at the acting leader)",
        )

        # Forensics plane (multiraft/forensics.py, ISSUE 15): offender
        # groups captured by the device black box, by safety slot —
        # HealthMonitor.record_incident increments by the newly-captured
        # delta, so the counter tracks cumulative distinct offenders.
        self.safety_incidents = r.counter(
            "multiraft_safety_incidents_total",
            "Safety-invariant offender groups captured by the black-box "
            "forensics layer, by slot",
            ("slot",),
        )

    # --- tracing ---

    def trace(self, event: str, **fields) -> None:
        if self.tracer is not None:
            self.tracer.emit(event, **fields)

    # --- scalar-core hooks (raft.py) ---

    def on_send(self, msg_type: int) -> None:
        self._sent_by_type[msg_type].inc()

    def on_recv(self, msg_type: int) -> None:
        self._recv_by_type[msg_type].inc()

    def on_transition(self, to_role: int, group: int, id: int, term: int) -> None:
        self._trans_by_role[to_role].inc()
        if self.tracer is not None:
            self.tracer.emit(
                "state_transition",
                group=group,
                id=id,
                term=term,
                to=self._role_names[to_role],
            )

    def on_campaign(self, kind: str, group: int, id: int, term: int) -> None:
        self.campaigns.labels(type=kind).inc()
        if self.tracer is not None:
            self.tracer.emit(
                "campaign", group=group, id=id, term=term, type=kind
            )

    def on_vote_grant(
        self, pre: bool, group: int, id: int, term: int, candidate: int
    ) -> None:
        self.votes_granted.labels(type="PreVote" if pre else "Vote").inc()
        if self.tracer is not None:
            self.tracer.emit(
                "vote_grant",
                group=group,
                id=id,
                term=term,
                candidate=candidate,
                pre=pre,
            )

    def on_election_won(self, group: int, id: int, term: int) -> None:
        self.elections_won.inc()

    def on_beat(self) -> None:
        self.beats.inc()

    def on_commit_advance(
        self, group: int, id: int, term: int, old: int, new: int
    ) -> None:
        self.commit_advances.inc()
        self.commit_entries.inc(new - old)
        if self.tracer is not None:
            self.tracer.emit(
                "commit_advance",
                group=group,
                id=id,
                term=term,
                old=old,
                new=new,
            )

    def on_append_rejected(self, group: int, id: int, term: int, index: int) -> None:
        self.appends_rejected.inc()
        if self.tracer is not None:
            self.tracer.emit(
                "append_rejected", group=group, id=id, term=term, index=index
            )

    def on_snapshot_sent(self, group: int, id: int, to: int, index: int) -> None:
        self.snapshots_sent.inc()
        if self.tracer is not None:
            self.tracer.emit(
                "snapshot_send", group=group, id=id, to=to, index=index
            )

    def on_conf_change(self, group: int, id: int, term: int) -> None:
        self.conf_changes.inc()
        if self.tracer is not None:
            self.tracer.emit("conf_change", group=group, id=id, term=term)

    # --- RawNode hooks (raw_node.py) ---

    def on_ready(self, must_sync: bool) -> None:
        self.ready_cycles.inc()
        if must_sync:
            self.must_sync.inc()

    def on_advance(self) -> None:
        self.advance_cycles.inc()

    # --- MultiRaft driver hooks (multiraft/driver.py) ---

    def on_driver_tick(
        self,
        n_active: int,
        n_campaign: int,
        n_beat: int,
        n_checkq: int,
        sync_seconds: float,
    ) -> None:
        self.driver_ticks.inc()
        self.driver_active_groups.inc(n_active)
        self.driver_campaigns_fired.inc(n_campaign)
        self.driver_beats_fired.inc(n_beat)
        self.driver_checkq_fired.inc(n_checkq)
        self.driver_last_active.set(n_active)
        self.driver_sync_seconds.observe(sync_seconds)

    def on_ready_scan(self, scanned: int, skipped: int) -> None:
        self.ready_scan_scanned.inc(scanned)
        self.ready_scan_skipped.inc(skipped)

    # --- fleet-health hooks (multiraft/health.py HealthMonitor) ---

    def on_health_summary(self, summary: dict) -> None:
        """Publish one fixed-size health summary (the dict shape produced
        by ClusterSim.health() / MultiRaft.health()) as gauges."""
        from .multiraft.kernels import LAG_BUCKET_BOUNDS

        self.health_summaries.inc()
        counts = summary.get("counts", {})
        self.health_leaderless.set(counts.get("leaderless", 0))
        self.health_stalled_leaderless.set(
            counts.get("stalled_leaderless", 0)
        )
        self.health_commit_stalled.set(counts.get("commit_stalled", 0))
        self.health_churning.set(counts.get("churning", 0))
        worst = summary.get("worst") or []
        if worst:
            self.health_worst_score.set(worst[0]["score"])
        bounds = (0,) + LAG_BUCKET_BOUNDS
        for lo, n in zip(bounds, summary.get("lag_hist", ())):
            self.health_commit_lag.labels(ge=lo).set(n)
